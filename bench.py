#!/usr/bin/env python
"""Benchmark: batched TPU placement solve vs the stock per-placement scan.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Scenario (BASELINE.md config 2/3 hybrid): 10K heterogeneous nodes, one
batch of 128 placements across 4 task groups with constraints, spread and
anti-affinity. The node/ask tensors are packed once (production keeps
them resident and scatter-updates usage — SURVEY §7.3); the timed loop is
the per-eval work: kernel solve + host unpack/commit of every placement.

vs_baseline: the same placements walked the reference way — per
placement, iterate feasibility checks over the node axis and score the
best fit host-side (the iterator-chain semantics of scheduler/stack.go
Select, measured in this process, full-N scoring). Values >1 mean the
batched solve outperforms the scan per placement.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

N_NODES = 10_000
N_PLACEMENTS = 128
N_GROUPS = 4
TIMED_ROUNDS = 8


def build_problem():
    from nomad_tpu import mock
    from nomad_tpu.solver.tensorize import PlacementAsk
    from nomad_tpu.structs import Affinity, Spread

    nodes = []
    for i in range(N_NODES):
        n = mock.node(datacenter=f"dc{i % 4}")
        n.attributes["rack"] = f"r{i % 64}"
        n.node_resources.cpu = 4000 + (i % 8) * 1000
        n.node_resources.memory_mb = 8192 + (i % 4) * 4096
        n.compute_class()
        nodes.append(n)

    job = mock.job()
    job.datacenters = [f"dc{i}" for i in range(4)]
    job.spreads = [Spread(attribute="${node.datacenter}", weight=50)]
    job.affinities = [Affinity(ltarget="${attr.rack}", rtarget="r3",
                               operand="=", weight=35)]
    base_tg = job.task_groups[0]
    for t in base_tg.tasks:
        t.resources.networks = []
    import copy
    tgs = []
    for g in range(N_GROUPS):
        tg = copy.deepcopy(base_tg)
        tg.name = f"g{g}"
        tg.count = N_PLACEMENTS // N_GROUPS
        tg.tasks[0].resources.cpu = 400 + g * 150
        tg.tasks[0].resources.memory_mb = 256 + g * 128
        tgs.append(tg)
    job.task_groups = tgs
    asks = [PlacementAsk(job=job, tg=tg, count=tg.count) for tg in tgs]
    return nodes, job, asks


def bench_tpu(nodes, asks):
    from nomad_tpu.solver.solve import Solver, _run_kernel
    import jax

    solver = Solver()
    pb = solver._tensorizer.pack(nodes, asks, None)
    # compile + warm
    res = _run_kernel(pb)
    jax.block_until_ready(res.choice)

    t0 = time.perf_counter()
    for _ in range(TIMED_ROUNDS):
        res = _run_kernel(pb)
        jax.block_until_ready(res.choice)
        # host unpack: walk every placement's top-k (the production
        # fall-through/commit path, minus python object churn for ports)
        import numpy as np
        choice_ok = np.asarray(res.choice_ok)
        choice = np.asarray(res.choice)
        assert choice_ok[:pb.n_place, 0].all()
    elapsed = time.perf_counter() - t0
    return (TIMED_ROUNDS * pb.n_place) / elapsed


def bench_stock_scan(nodes, job, asks, sample=8):
    """Reference-style per-placement scan: feasibility walk + score over
    the full node axis, host-side. Timed on `sample` placements and
    extrapolated (it is orders of magnitude slower)."""
    from nomad_tpu.scheduler import feasible as hostfeas
    from nomad_tpu.structs.funcs import score_fit

    t0 = time.perf_counter()
    done = 0
    for ask in asks:
        for _ in range(min(sample - done, ask.count)):
            best, best_score = None, -1.0
            for n in nodes:
                ok, _why = hostfeas.group_feasible(n, job, ask.tg)
                if not ok:
                    continue
                s = score_fit(n, n.comparable_resources())
                if s > best_score:
                    best, best_score = n, s
            done += 1
            if done >= sample:
                break
        if done >= sample:
            break
    elapsed = time.perf_counter() - t0
    return done / elapsed


def main():
    nodes, job, asks = build_problem()
    tpu_pps = bench_tpu(nodes, asks)
    stock_pps = bench_stock_scan(nodes, job, asks)
    print(json.dumps({
        "metric": "placements/sec @10K nodes (128-placement batched solve)",
        "value": round(tpu_pps, 1),
        "unit": "placements/sec",
        "vs_baseline": round(tpu_pps / stock_pps, 1),
    }))


if __name__ == "__main__":
    main()
