#!/usr/bin/env python
"""Benchmark: the TPU placement pipeline vs stock scheduler semantics.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
and writes the full per-config results to BENCH_DETAIL.json.

Configs follow BASELINE.md's measurement plan:
  1. 1 service job x 10 task groups on 100 in-mem nodes (latency mode)
  2. 10K nodes, 50K resident allocs - pure bin-pack stream
  3. 10K heterogeneous nodes, 100K resident allocs - constraints +
     affinity + spread + anti-affinity (the primary config)
  4. device scheduling - TPU inventory on every 4th node
  5. multi-region federation - 4 regions x 10K nodes

The DENOMINATOR is honest per VERDICT r2: bench/stock_engine.cc, a
faithful C++ implementation of the reference's placement semantics AND
data layout (string-keyed state, per-eval shuffled node order, lazy
class-memoized feasibility, limit = max(2, ceil(log2 N)) subsampled
ranking - scheduler/stack.go:80-87 - proposed-alloc bin-packing, serial
re-validating plan applier). C++ stands in for Go at comparable speed;
the scenario generators on both sides share the same formulas, so the
engines see identical clusters and jobs.

The NUMERATOR is the production ResidentSolver streaming path: node
tensors packed and device-put once, ask programs packed per eval batch,
usage carried on device, many batches fused per device call, one packed
result fetch. Timings include ask packing, transfer, solve, and result
fetch - everything after one-time startup (reported separately).

Both throughput (fused streams) and latency (single-eval calls) are
measured; placement-QUALITY is compared with a pack-to-capacity duel
(the stock path ranks ~14 of N nodes; this solve scores all N).
"""
import json
import math
import os
import statistics
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO = os.path.dirname(os.path.abspath(__file__))


def _enable_compile_cache():
    # shared opt-in util (utils/compile_cache): agent config / env can
    # point it anywhere durable; the bench defaults it on so warm
    # restarts measure the failover-relevant startup
    from nomad_tpu.utils.compile_cache import enable_compile_cache
    return enable_compile_cache(
        os.environ.get("NOMAD_TPU_COMPILE_CACHE")
        or "/tmp/nomad_tpu_jax_cache")


_enable_compile_cache()


def _cache_report(entries_before):
    """Compile-cache hit/miss report for the startup line: programs
    persisted during THIS startup are misses; a fully warm start adds
    none."""
    from nomad_tpu.utils.compile_cache import (cache_entries,
                                               enable_compile_cache)
    d = enable_compile_cache(None)
    added = cache_entries() - entries_before
    return {"dir": d, "entries_before": entries_before,
            "compiles_persisted": added, "warm_start": added == 0}
STOCK_BIN = os.path.join(REPO, "bench", "stock_engine")
STOCK_SRC = os.path.join(REPO, "bench", "stock_engine.cc")

R_VEC = [200.0, 256.0, 300.0, 0.0]       # resident alloc usage vector


def pct(sorted_ms, p):
    """Nearest-rank percentile over an ASCENDING ms list (the shared
    helper every phase uses — previously copied per phase)."""
    return sorted_ms[int(p * (len(sorted_ms) - 1))] if sorted_ms else 0.0


def latency_summary(latencies_s):
    """p50/p99 (ms) of a latency sample in seconds — the one latency
    summary used by the closed-loop, latency-mode, and open-loop
    phases."""
    lat_ms = sorted(1000.0 * x for x in latencies_s)
    return {"p50_ms": round(pct(lat_ms, 0.5), 3),
            "p99_ms": round(pct(lat_ms, 0.99), 3)}


# ---------------- scenario (mirrors stock_engine.cc) ----------------

def make_nodes(n_nodes, devices=False, gen_seed=0):
    from nomad_tpu import mock
    nodes = []
    for i in range(n_nodes):
        n = mock.node(datacenter=f"dc{i % 4}")
        # identical effective capacity on both engines: the stock C++
        # generator models no reserved carve-out, and a 100-cpu/node
        # difference alone decides the pack-to-capacity duel (256
        # placements at 512 nodes) — zero it here rather than compare
        # engines against different clusters
        n.reserved_resources.cpu = 0
        n.reserved_resources.memory_mb = 0
        n.reserved_resources.disk_mb = 0
        n.attributes["kernel.name"] = "linux"
        n.attributes["rack"] = f"r{i % 64}"
        n.attributes["zone"] = f"z{i % 16}"
        n.node_resources.cpu = 4000 + ((i + gen_seed) % 8) * 1000
        n.node_resources.memory_mb = 8192 + ((i + gen_seed * 3) % 4) * 4096
        n.node_resources.disk_mb = 100_000
        for net in n.node_resources.networks:
            net.mbits = 1000
        if devices and i % 2 == 0:
            from nomad_tpu.structs import NodeDeviceResource, NodeDevice
            n.node_resources.devices = [NodeDeviceResource(
                vendor="google", type="tpu", name="v4",
                instances=[NodeDevice(id=f"tpu-{i}-{k}", healthy=True)
                           for k in range(8)])]
        n.compute_class()
        nodes.append(n)
    return nodes


def make_job(config, eval_ix, count, gen_seed=0):
    """Mirrors stock_engine.cc make_job exactly."""
    from nomad_tpu import mock
    from nomad_tpu.structs import Affinity, Constraint, RequestedDevice, \
        Spread
    job = mock.job()
    job.id = f"job-{config}-{eval_ix}"
    job.name = job.id
    job.datacenters = [f"dc{d}" for d in range(4)]
    job.constraints = []
    job.affinities = []
    job.spreads = []
    base = job.task_groups[0]
    base.constraints = []

    def group(name, cnt, cpu, mem, devices=0):
        import copy
        tg = copy.deepcopy(base)
        tg.name = name
        tg.count = cnt
        tg.constraints = []
        t = tg.tasks[0]
        t.resources.networks = []
        t.resources.cpu = cpu
        t.resources.memory_mb = mem
        t.resources.devices = ([RequestedDevice(name="google/tpu/v4",
                                                count=devices)]
                               if devices else [])
        tg.ephemeral_disk.size_mb = 300
        return tg

    if config == 1:
        job.constraints = [Constraint("${attr.kernel.name}", "linux", "=")]
        job.task_groups = [
            group(f"g{g}", max(1, count // 10),
                  400 + ((g + gen_seed) % 4) * 150,
                  256 + ((g + gen_seed) % 4) * 128)
            for g in range(10)]
        return job
    if config == 3:
        job.constraints = [
            Constraint("${attr.rack}", "r63", "!="),
            Constraint("${attr.zone}", "z1", ">="),      # lexical
        ]
        job.affinities = [Affinity(ltarget="${attr.rack}", rtarget="r7",
                                   operand="=", weight=35)]
        job.spreads = [Spread(attribute="${node.datacenter}", weight=50)]
        job.task_groups = [
            group(f"g{g}", count // 4,
                  400 + ((g + gen_seed) % 4) * 150,
                  256 + ((g + gen_seed) % 4) * 128)
            for g in range(4)]
        return job
    dev = 1 if config == 4 else 0
    job.task_groups = [group("g0", count, 400, 256, devices=dev)]
    return job


def resident_used0(template, n_nodes, resident):
    import numpy as np
    used0 = np.zeros_like(template.used0)
    counts = np.bincount(np.arange(resident) % n_nodes,
                         minlength=n_nodes).astype(np.float32)
    used0[:n_nodes] = counts[:, None] * np.asarray(R_VEC, np.float32)
    return used0


# ---------------- numerator: resident streaming pipeline -------------

def asks_for(job):
    from nomad_tpu.solver.tensorize import PlacementAsk
    return [PlacementAsk(job=job, tg=tg, count=tg.count)
            for tg in job.task_groups]


def _steady_alloc():
    """A plan-apply-feedback alloc for the steady-state delta waves."""
    from nomad_tpu import mock
    a = mock.alloc()
    tr = a.allocated_resources.tasks["web"]
    tr.cpu, tr.memory_mb, tr.networks = 200, 256, []
    a.allocated_resources.shared.networks = []
    a.allocated_resources.shared.disk_mb = 300
    return a


def _harvest(status_row, pb, asks, STATUS_RETRY):
    """Vectorized per-batch result accounting: (placed, failed,
    [(ask, retry_count), ...])."""
    import numpy as np
    st = status_row[:pb.n_place]
    placed = int((st == 1).sum())
    failed = int((st == 0).sum())
    retry_mask = st == STATUS_RETRY
    if not retry_mask.any():
        return placed, failed, []
    per_ask = np.bincount(pb.p_ask[:pb.n_place][retry_mask],
                          minlength=len(asks))
    return placed, failed, [(a, int(r))
                            for a, r in zip(asks, per_ask) if r]


def run_ours(config, n_nodes, n_evals, count, resident,
             evals_per_call=128, exact=False, gen_seed=0,
             pallas="auto"):
    """Drive the ResidentSolver streaming pipeline over the config's
    eval workload.

    Throughput mode is PIPELINED: each chunk of evals packs on the host
    and dispatches immediately as its own chained device call (JAX
    dispatch is async and chained calls add no round trip — the carried
    usage serializes them on device), so packing rides entirely under
    the previous chunks' solve; ONE concatenated result fetch then pays
    the transport round trip once for the whole workload.  Wave-budget
    leftovers drain in follow-up calls.  Exact mode (quality duel)
    keeps the single fused call.  Returns metrics dict."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np
    from nomad_tpu.solver.resident import (ResidentSolver, STATUS_RETRY)

    devices = config == 4
    nodes = make_nodes(n_nodes, devices=devices, gen_seed=gen_seed)
    from nomad_tpu.utils.compile_cache import cache_entries
    cache0 = cache_entries()
    t0 = time.perf_counter()
    probe_job = make_job(config, 0, count, gen_seed=gen_seed)
    epc = min(evals_per_call, n_evals)
    # throughput mode merges identical fresh asks at pack time (the
    # columnar payoff of coalescing evals: G shrinks to the number of
    # DISTINCT ask shapes, and every per-wave [G, N] pass shrinks with
    # it); exact mode keeps one group per ask
    merge = not exact
    kp_need = count * epc
    if merge:
        # size the group axis to the workload's REAL distinct-shape
        # count: every per-wave [G, N] pass scales with gp, and the
        # merged stream needs exactly one row per distinct signature
        # (config 2/4: 1, config 3: 4) — not the MERGED_GP_MAX=16 cap.
        # Every eval's job has the same shape, so one job's signature
        # set sizes the whole stream (all bench asks are stateless).
        from nomad_tpu.solver.tensorize import Tensorizer
        gp_need = len({Tensorizer.ask_signature(a)
                       for a in asks_for(probe_job)})
    else:
        gp_need = len(probe_job.task_groups) * epc
    # exact mode uses serial-fidelity stacking commits (the reference's
    # per-placement best-fit packing — placement QUALITY over wave
    # count), with a budget deep enough to stack a full group
    rs = ResidentSolver(nodes, asks_for(probe_job),
                        gp=1 << max(0, (gp_need - 1).bit_length()),
                        kp=1 << max(0, (kp_need - 1).bit_length()),
                        max_waves=(24 if exact else 18),
                        stack_commit=exact, pallas=pallas)
    rs.reset_usage(used0=resident_used0(rs.template, n_nodes, resident))

    # build the whole eval workload up front (job objects are cheap)
    jobs = [make_job(config, e, count, gen_seed=gen_seed)
            for e in range(n_evals)]

    # single-fetch helper for drain rounds (the main pipelined stream's
    # concatenated fetch lives in ResidentSolver.solve_stream_pipelined)
    stack_jit = jax.jit(lambda *xs: jnp.stack(xs))

    NB = -(-n_evals // epc)
    # warm the compiles with the real batch shapes, then reset: the
    # stream shapes (B=1 chained calls in merge mode, one fused B=NB
    # call in exact mode), the concat/stack fetch arities, and the
    # drain-path variants (small per-group counts -> the kernel's floor
    # group_count_hint bucket)
    warm_asks = sum((asks_for(j) for j in jobs[:epc]), [])
    if merge:
        warm_asks, _wk = rs.merge_asks(warm_asks)
    warm = rs.pack_batch(warm_asks)
    warm.job_keys = None        # compile-only: bypass the same-job guard
    if merge:
        # warms the B=1 chained-call kernel AND the solver's own
        # concatenated-fetch jit at the real arity
        rs.solve_stream_pipelined([warm] * NB,
                                  seeds=[b + 1 for b in range(NB)])
    else:
        np.asarray(rs.solve_stream_async([warm] * NB, seeds=None))
    wout_b1 = rs.solve_stream_async([warm], seeds=None if exact else [1])
    for nd in (1, 2, 3, 4):     # drain fetch stacks (B=1 calls)
        np.asarray(stack_jit(*([wout_b1] * nd)))
    drain_warm_asks = [dataclasses.replace(a, count=min(a.count, 8))
                       for a in (warm_asks[:2] or warm_asks)]
    dwarm = rs.pack_batch(drain_warm_asks)
    if dwarm is not None:
        dwarm.job_keys = None
        rs.solve_stream([dwarm], seeds=None if exact else [1])
    rs.reset_usage(used0=resident_used0(rs.template, n_nodes, resident))
    startup_s = time.perf_counter() - t0

    placed = failed = retried = unresolved = 0
    n_fetches = 0
    n_dispatches = 0
    pack_s = dispatch_s = 0.0
    t_start = time.perf_counter()
    asks_all = []
    batches = []

    def pack_one(i):
        asks = sum((asks_for(j) for j in jobs[i:i + epc]), [])
        keys = None
        if merge:
            asks, keys = rs.merge_asks(asks)
        # the whole-batch cache only suits the pipelined one-batch-per-
        # call schedule; exact mode fuses MANY batches into one call and
        # a shared pb object would confuse the same-job stream guard
        pack = rs.pack_batch_cached if merge else rs.pack_batch
        pb = pack(asks, job_keys=keys)
        assert pb is not None, "bench asks must fit the universe"
        asks_all.append(asks)
        batches.append(pb)
        return pb

    if merge:
        # pipelined: pack chunk b+1 while chunk b solves (chained
        # dispatches, no host sync), then ONE concatenated fetch —
        # the double-buffered pack→dispatch overlap now lives in
        # ResidentSolver.solve_stream_pipelined
        _, _, _, status = rs.solve_stream_pipelined(
            [b * epc for b in range(NB)],
            seeds=[b + 1 for b in range(NB)], pack=pack_one)
        st = rs.last_pipeline_stats
        pack_s += st["pack_s"]
        dispatch_s += st["dispatch_s"]
        fetch_wait_s = st["fetch_s"]
        n_dispatches += st["n_dispatches"]
        n_fetches += 1
    else:
        t_p = time.perf_counter()
        for b in range(NB):
            pack_one(b * epc)
        t_d = time.perf_counter()
        out1 = rs.solve_stream_async(batches, seeds=None)
        n_dispatches += 1
        t_f = time.perf_counter()
        packed = np.asarray(out1)                      # ONE fetch
        fetch_wait_s = time.perf_counter() - t_f
        pack_s = t_d - t_p
        dispatch_s = t_f - t_d
        n_fetches += 1
        status = packed[:, :, -1].astype(np.int32)     # [NB, K]

    # wave-budget leftovers: resubmit ONLY the undecided counts, all
    # batches' leftovers fused into one reduced batch per drain round
    # (counted in the timing)
    cur = []                    # (ask, retry_count) flattened
    for b, pb in enumerate(batches):
        pl, fl, retries = _harvest(status[b], pb, asks_all[b],
                                   STATUS_RETRY)
        placed += pl
        failed += fl
        cur.extend(retries)
    gp_cap, kp_cap = rs.gp, rs.kp
    for t_retry in range(4):
        if not cur:
            break
        retried += sum(r for _, r in cur)
        # keep every drain row's count inside the kernel's floor-64
        # group_count_hint bucket (the ONLY drain variant the warm block
        # compiled): a bigger retry count splits into <=64-count rows —
        # same merged-population semantics, no compile in the timed
        # region.  Exact mode never splits (counts are already <=64).
        if merge:
            # merged drain rows are stateless by merge eligibility, so
            # they may span chunks freely: flatten the splits, then fill
            # chunks greedily under the gp/kp caps
            split = []
            for a, r in cur:
                while r > 64:
                    split.append(dataclasses.replace(a, count=64))
                    r -= 64
                split.append(dataclasses.replace(a, count=r))
            chunks, cur_chunk, cur_k = [], [], 0
            for a in split:
                if cur_chunk and (len(cur_chunk) + 1 > gp_cap
                                  or cur_k + a.count > kp_cap):
                    chunks.append(cur_chunk)
                    cur_chunk, cur_k = [], 0
                cur_chunk.append(a)
                cur_k += a.count
            if cur_chunk:
                chunks.append(cur_chunk)
        else:
            # exact mode: asks may carry job-scoped state — a job's
            # asks stay in ONE chunk (stream invariant)
            drain_asks = [dataclasses.replace(a, count=r)
                          for a, r in cur]
            by_job = {}
            for a in drain_asks:
                by_job.setdefault((a.job.namespace, a.job.id),
                                  []).append(a)
            chunks, cur_chunk, cur_k = [], [], 0
            for job_asks in by_job.values():
                jk = sum(a.count for a in job_asks)
                if cur_chunk and (len(cur_chunk) + len(job_asks) > gp_cap
                                  or cur_k + jk > kp_cap):
                    chunks.append(cur_chunk)
                    cur_chunk, cur_k = [], 0
                cur_chunk.extend(job_asks)
                cur_k += jk
            if cur_chunk:
                chunks.append(cur_chunk)
        pbs = [rs.pack_batch(c) for c in chunks]
        assert all(pb is not None for pb in pbs), \
            "drain chunk fell outside the resident universe"
        douts = []
        for i, pb in enumerate(pbs):
            douts.append(rs.solve_stream_async(
                [pb], seeds=None if exact else [1009 + 17 * t_retry + i]))
            n_dispatches += 1
        # fetch in warmed-arity groups (the warm block compiled stack
        # arities 1-4): a heavy drain round must never compile inside
        # the timed region
        drows = []
        for i in range(0, len(douts), 4):
            grp = douts[i:i + 4]
            drows.append(np.asarray(stack_jit(*grp)))
            n_fetches += 1
        dpacked = np.concatenate(drows, axis=0)
        dstatus = dpacked[:, 0, :, -1].astype(np.int32)
        nxt = []
        for b, (pb, chunk) in enumerate(zip(pbs, chunks)):
            pl, fl, retries = _harvest(dstatus[b], pb, chunk,
                                       STATUS_RETRY)
            placed += pl
            failed += fl
            nxt.extend(retries)
        cur = nxt
    # anything still RETRY after the retry budget is reported, not
    # silently dropped (placed + failed + unresolved == workload)
    unresolved += sum(r for _, r in cur)
    total_evals = n_evals
    elapsed_all = time.perf_counter() - t_start

    # ---- steady-state delta waves (ISSUE 2 acceptance) ----
    # The store-stable-jobs regime: the SAME eval population
    # re-dispatched (blocked-eval retries, drain re-evals, rollouts)
    # with a plan-apply usage changeset applied between waves.  Packing
    # is the eval-cache hit, dispatch re-ships nothing (device-cached
    # stacked args), and the device scatters only the delta rows —
    # measured against the first-pass per-wave pack+dispatch cost.
    steady = None
    if merge and batches:
        from nomad_tpu.solver.tensorize import ClusterDelta
        n_steady = min(4, len(batches))
        # warm the scatter-apply kernels at the steady shape (pow2-
        # padded slot cardinality) outside the timed region
        warm_d = ClusterDelta()
        for k in range(32):
            nid = nodes[(k * 41 + 3) % n_nodes].id
            a = _steady_alloc()
            warm_d.place.append((nid, a))
            warm_d.stop.append((nid, a))
        rs.apply_delta(warm_d)
        deltas = []
        for w in range(n_steady):
            d = ClusterDelta()
            for k in range(32):
                nid = nodes[(w * 977 + k * 131) % n_nodes].id
                a = _steady_alloc()
                d.place.append((nid, a))
                d.stop.append((nid, a))   # net-zero churn: place+stop
            deltas.append(d)
        t_s = time.perf_counter()
        rs.solve_stream_pipelined(
            batches[:n_steady], seeds=[7001 + b for b in range(n_steady)],
            deltas=deltas)
        steady_elapsed = time.perf_counter() - t_s
        st = rs.last_pipeline_stats
        main_pd = (pack_s + dispatch_s) / max(n_dispatches, 1)
        steady_pd = (st["pack_s"] + st["dispatch_s"]) / n_steady
        steady = {
            "waves": n_steady,
            "pack_ms_per_wave": round(1000 * st["pack_s"] / n_steady, 3),
            "dispatch_ms_per_wave": round(
                1000 * st["dispatch_s"] / n_steady, 3),
            "delta_apply_ms_per_wave": round(
                1000 * st["delta_apply_s"] / n_steady, 3),
            "bytes_dispatched_delta_waves": st["bytes_dispatched"],
            "elapsed_s": round(steady_elapsed, 4),
            "first_pass_pack_dispatch_ms_per_wave": round(
                1000 * main_pd, 3),
            "steady_pack_dispatch_ms_per_wave": round(
                1000 * steady_pd, 3),
            "pack_dispatch_reduction": round(
                main_pd / max(steady_pd, 1e-9), 1),
        }
    # every eval in a fused call completes when the call completes
    latencies = [elapsed_all] * n_evals
    elapsed = elapsed_all
    lat = latency_summary(latencies)

    return {
        "engine": "nomad-tpu resident stream",
        "evals": total_evals, "placements": placed, "failed": failed,
        "retried": retried, "unresolved": unresolved,
        "n_device_calls": n_fetches, "n_dispatches": n_dispatches,
        "breakdown_ms": {
            "pack": round(1000 * pack_s, 1),
            "dispatch": round(1000 * dispatch_s, 1),
            "solve_and_fetch_wait": round(1000 * fetch_wait_s, 1),
        },
        "steady_state": steady,
        "delta_counters": dict(rs.delta_counters),
        "compile_cache": _cache_report(cache0),
        "elapsed_s": round(elapsed, 4),
        "startup_s": round(startup_s, 2),
        "evals_per_sec": round(total_evals / elapsed, 1),
        "placements_per_sec": round(placed / elapsed, 1),
        "p50_ms": lat["p50_ms"], "p99_ms": lat["p99_ms"],
        "nodes_scored_per_placement": n_nodes,
    }


def measure_device_ceiling(config=3):
    """Device-only solve ceiling for one config (VERDICT r4 item 1):
    every argument resident on device, chained re-runs, the transport
    round trip subtracted — placements/s with transport at zero.  Plus
    a memory-roofline estimate of ONE wave so the distance from the
    chip is explicit: the wave's dominant traffic is the [G, N] score/
    feasibility passes (f32) + the [N, R] usage updates, far below
    MXU-relevant arithmetic intensity — the kernel is HBM-bound by
    design, so the roofline is bytes/bandwidth, not FLOPs."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from nomad_tpu.solver.resident import ResidentSolver, _stream_kernel
    from nomad_tpu.solver.tensorize import Tensorizer

    p = CONFIGS[config]
    n_nodes, n_evals, count, resident = (p["n_nodes"], p["n_evals"],
                                         p["count"], p["resident"])
    epc = min(128, n_evals)
    NB = -(-n_evals // epc)
    nodes = make_nodes(n_nodes, devices=config == 4)
    probe_job = make_job(config, 0, count)
    gp_need = len({Tensorizer.ask_signature(a)
                   for a in asks_for(probe_job)})
    rs = ResidentSolver(nodes, asks_for(probe_job),
                        gp=1 << max(0, (gp_need - 1).bit_length()),
                        kp=1 << max(0, (count * epc - 1).bit_length()),
                        max_waves=18)
    used0 = resident_used0(rs.template, n_nodes, resident)
    rs.reset_usage(used0=used0)
    jobs = [make_job(config, e, count) for e in range(n_evals)]
    batches = []
    for i in range(0, n_evals, epc):
        asks, keys = rs.merge_asks(
            sum((asks_for(j) for j in jobs[i:i + epc]), []))
        batches.append(rs.pack_batch(asks, job_keys=keys))
    stacked = rs._stack_args(batches)
    dev = {k: (jax.device_put(v) if isinstance(v, np.ndarray) else v)
           for k, v in stacked.items()}
    n_places = np.asarray([pb.n_place for pb in batches], np.int32)
    seeds = np.asarray(range(1, NB + 1), np.int32)
    kw = dict(has_spread=rs._has_spread(batches),
              group_count_hint=rs._group_count_hint(batches),
              max_waves=rs.max_waves, wave_mode=rs.wave_mode,
              has_distinct=rs._has_distinct(batches),
              has_devices=rs._has_devices(batches),
              stack_commit=False, compact=rs._compact,
              pallas_mode=rs.pallas, shortlist_c=rs.shortlist_c)
    args = (rs._dev_node["avail"], rs._dev_node["reserved"],
            rs._dev_node["valid"], rs._dev_node["node_dc"],
            rs._dev_node["attr_rank"], rs._dev_node["dev_cap"])
    rtt = measure_transport_rtt()
    ts = []
    waves_total = rescore_total = 0
    for trial in range(4):
        rs.reset_usage(used0=used0)
        t0 = time.perf_counter()
        _u, _d, o, w, rw = _stream_kernel(*args, rs._used, rs._dev_used,
                                          dev, n_places, seeds, **kw)
        np.asarray(o)
        ts.append(time.perf_counter() - t0)
        waves_total = int(np.asarray(w).sum())   # same every trial
        rescore_total = int(np.asarray(rw).sum())
    solve_s = max(min(ts[1:]) - rtt, 1e-6)   # trial 0 warms the compile
    placements = int(n_places.sum())

    # two-tier per-wave memory model (resident.wave_traffic: full-N
    # first/rescore waves vs shortlist-resident contention waves) ×
    # MEASURED per-batch wave counters gives the achieved-bandwidth
    # figure the roofline claim is audited by.  Counters come from the
    # stream kernel in EVERY pallas mode (off/score/topk), so no field
    # here is ever left pending.
    traffic = rs.wave_traffic(batches)
    b_wave1 = traffic["bytes_wave1"]
    b_rewave = traffic["bytes_rewave"]
    sl_waves = waves_total - rescore_total
    bytes_total = b_wave1 * rescore_total + b_rewave * sl_waves
    HBM_GBPS = 819.0                    # v5e-class HBM bandwidth
    wave_floor_us = b_wave1 / (HBM_GBPS * 1e3)
    achieved_gbps = bytes_total / solve_s / 1e9
    # the merged-throughput stream carries a 1024-wide candidate
    # window, and bit-identity pins the shortlist at C >= TK — the
    # rewave reduction there is window-bounded.  The STANDARD window
    # (exact/interactive regime, the quality duel's shape) is where the
    # shortlist's full cut shows; model it at this config's node scale
    # so the two regimes sit side by side in the record.
    from nomad_tpu.solver.kernel import resolve_shortlist_c
    from nomad_tpu.solver.resident import model_wave_bytes
    t = rs.template
    S = t.sp_desired.shape[1]
    Np_pad = t.avail.shape[0]
    TK_std = 132
    C_std = resolve_shortlist_c(Np_pad, TK_std, 0)
    Gp_m = max(pb.ask_res.shape[0] for pb in batches)
    sb1, sbrw, _ = model_wave_bytes(
        Np_pad, Gp_m, 256, S, t.avail.shape[1],
        rs._has_spread(batches), traffic["mode"], TK_std, C_std)
    std_window = {
        "window_tk": TK_std, "shortlist_c": C_std,
        "bytes_wave1": sb1, "bytes_rewave": sbrw,
        "rewave_reduction": round(sb1 / max(sbrw, 1), 1),
    }
    return {
        "config": config,
        "device_only_solve_s": round(solve_s, 4),
        "device_only_placements_per_sec": round(placements / solve_s, 1),
        "transport_rtt_ms": round(1000 * rtt, 1),
        "roofline": {
            "wave_bytes_est": b_wave1,
            "bytes_wave1": b_wave1,
            "bytes_rewave": b_rewave,
            "rewave_reduction": round(b_wave1 / max(b_rewave, 1), 1),
            "shortlist_c": traffic["shortlist_c"],
            "waves_total": waves_total,
            "rescore_waves": rescore_total,
            "shortlist_waves": sl_waves,
            "modeled_bytes_total": int(bytes_total),
            "hbm_gbps_assumed": HBM_GBPS,
            "achieved_hbm_gbps": round(achieved_gbps, 1),
            "wave_floor_us_est": round(wave_floor_us, 1),
            "pallas_mode": traffic["mode"],
            "tile_size": traffic["tile"],
            "fused_pass_count": traffic["fused_pass_count"],
            "standard_window": std_window,
            "note": ("the wave kernel is HBM-bound; the floor is "
                     "bytes_wave1 + bytes_rewave x (waves - 1) per "
                     "batch over bandwidth.  Full-N passes run on wave "
                     "1 and on every shortlist-escape rescore "
                     "(rescore_waves); the remaining contention waves "
                     "re-rank the carried top-C shortlist in VMEM "
                     "(bytes_rewave, kernel.py).  achieved_hbm_gbps = "
                     "(bytes_wave1 x rescore_waves + bytes_rewave x "
                     "shortlist_waves) / solve_s, read against "
                     "hbm_gbps_assumed"),
        },
    }


def run_multichip(n_devices=8, sizes=None, n_evals=16, count=64,
                  evals_per_call=8, write_detail=True, n_hosts=None):
    """Multichip phase (ISSUE 5): the mesh-resident sharded solve vs
    the stateless GSPMD wrapper, per node-scale.

    Per size: pack once, then (a) the stateless path — one
    `sharded_solve` per eval batch, re-shipping the whole packed batch
    every call and leaving the collectives to XLA — and (b) the
    mesh-resident path — ShardedResidentSolver.solve_stream with the
    node planes living sharded in HBM and candidate-only ICI traffic.
    Both are timed steady-state (round 2, after the compile round).
    The record carries solve timings, per-shard HBM bytes, and the
    modeled ICI bytes with the candidate-keys acceptance check
    (`ici_within_bound`: bytes_ici_per_wave <= TK_local x G x devices
    x key_bytes — no [G, N] plane crosses chips).

    Self-provisions a virtual n-device CPU platform when fewer real
    chips are attached (same forcing as the graft dryrun) — the phase
    can NOT silently skip on a 1-device host.  Sizes default to the
    50k/100k-node configs (NOMAD_TPU_MULTICHIP_NODES overrides)."""
    import importlib
    graft = importlib.import_module("__graft_entry__")
    if n_hosts is None:
        # dcn_tier leg (ISSUE 8): simulated host grouping on the CPU
        # mesh — NOMAD_TPU_MESH_HOSTS overrides the default 4
        from nomad_tpu.parallel.sharded import env_mesh_hosts
        n_hosts = env_mesh_hosts() or 4
    n_devices, n_hosts = graft._ensure_devices(n_devices, n_hosts)
    import jax
    import numpy as np
    from nomad_tpu.parallel.sharded import (
        ElasticShardedResidentSolver, ShardedResidentSolver,
        kernel_args, make_mesh, make_node_mesh, make_two_tier_mesh,
        sharded_solve_args)
    from nomad_tpu.solver.tensorize import Tensorizer

    if sizes is None:
        raw = os.environ.get("NOMAD_TPU_MULTICHIP_NODES", "50000,100000")
        sizes = [int(s) for s in raw.split(",") if s.strip()]
    out = {"phase": "multichip", "n_devices": int(n_devices),
           "n_hosts": int(n_hosts), "skipped": False,
           "backend": jax.default_backend(), "configs": []}
    mesh_stateless = make_mesh(n_devices, n_regions=1)
    for n_nodes in sizes:
        nodes = make_nodes(n_nodes)
        probe_job = make_job(2, 0, count)
        gp_need = len({Tensorizer.ask_signature(a)
                       for a in asks_for(probe_job)})
        epc = min(evals_per_call, n_evals)
        rs = ShardedResidentSolver(
            nodes, asks_for(probe_job),
            n_devices=n_devices,
            gp=1 << max(0, (gp_need - 1).bit_length()),
            kp=1 << max(0, (count - 1).bit_length()),
            max_waves=18, pallas="off")
        jobs = [make_job(2, e, count) for e in range(n_evals)]
        # pack_batch (not _cached): the cached path dedups the
        # identical-signature jobs to ONE PackedBatch, which the
        # same-job stream guard rightly rejects inside a chunk
        batches = [rs.pack_batch(asks_for(j)) for j in jobs]
        assert all(pb is not None for pb in batches)
        NB = -(-n_evals // epc)

        # ---- stateless wrapper: re-ship + re-solve per batch ----
        t_stateless = None
        stateless_bytes = sum(int(np.asarray(a).nbytes)
                              for a in kernel_args(batches[0]))
        for round_ in range(2):          # round 0 compiles
            t0 = time.perf_counter()
            last = None
            for pb in batches:
                last = sharded_solve_args(kernel_args(pb),
                                          mesh_stateless)
            jax.block_until_ready(last.choice)
            t_stateless = time.perf_counter() - t0

        # ---- mesh-resident stream ----
        t_resident = None
        resident_bytes = 0
        for round_ in range(2):
            rs.reset_usage()
            t0 = time.perf_counter()
            outs = []
            resident_bytes = 0
            for b in range(NB):
                chunk = batches[b * epc:(b + 1) * epc]
                outs.append(rs.solve_stream_async(chunk))
                resident_bytes += rs.last_dispatch_bytes
            jax.block_until_ready(outs[-1])
            t_resident = time.perf_counter() - t0
        wt = rs.wave_traffic(batches[:epc])
        ici = wt["ici"]
        rec = {
            "n_nodes": n_nodes,
            "np_padded": int(rs.template.avail.shape[0]),
            "n_evals": n_evals, "count": count,
            "stateless_wrapper_s": round(t_stateless, 4),
            "mesh_resident_s": round(t_resident, 4),
            "steady_state_speedup": round(
                t_stateless / max(t_resident, 1e-9), 2),
            # host->device bytes per eval: the stateless wrapper
            # re-ships the WHOLE packed batch (node planes included)
            # every solve; the resident path ships only the ask side.
            # On a virtual CPU mesh "shipping" is a same-host memcpy,
            # so wall-clock understates this gap — the byte counters
            # are the platform-independent transport story.
            "stateless_bytes_per_eval": int(stateless_bytes),
            "resident_bytes_per_eval": int(
                resident_bytes / max(n_evals, 1)),
            "ship_reduction_x": round(
                stateless_bytes * n_evals / max(resident_bytes, 1), 1),
            "per_shard_hbm": wt["per_shard"],
            "ici": ici,
            "ici_within_bound": bool(
                ici["bytes_ici_per_wave"]
                <= ici["bound_candidate_keys"]),
            "measured": wt.get("measured"),
        }

        # ---- dcn_tier leg (ISSUE 8): two-tier hierarchical exchange
        # on a simulated host grouping, vs the flat PR-5 exchange.
        # Plain ShardedResidentSolver on the two-tier mesh: same
        # extraction semantics as the flat run (incl. the approx_max_k
        # window at large Np), so the parity spot check is exact ----
        if n_hosts > 1 and n_devices % n_hosts == 0:
            rs2 = ShardedResidentSolver(
                nodes, asks_for(probe_job),
                mesh=make_two_tier_mesh(n_hosts, n_devices),
                gp=1 << max(0, (gp_need - 1).bit_length()),
                kp=1 << max(0, (count - 1).bit_length()),
                max_waves=18, pallas="off")
            b2 = [rs2.pack_batch(asks_for(j)) for j in jobs]
            t_tiered = None
            for round_ in range(2):
                rs2.reset_usage()
                t0 = time.perf_counter()
                outs2 = []
                for b in range(NB):
                    outs2.append(rs2.solve_stream_async(
                        b2[b * epc:(b + 1) * epc]))
                jax.block_until_ready(outs2[-1])
                t_tiered = time.perf_counter() - t0
            # placement parity spot check vs the flat mesh run
            rs.reset_usage()
            rs2.reset_usage()
            c1, o1, _, st1 = rs.solve_stream(batches[:epc])
            c2, o2, _, st2 = rs2.solve_stream(b2[:epc])
            parity = bool(np.array_equal(o1, o2)
                          and np.array_equal(st1, st2)
                          and np.array_equal(np.where(o1, c1, -1),
                                             np.where(o2, c2, -1)))
            wt2 = rs2.wave_traffic(b2[:epc])
            dcn = wt2["dcn"]
            rec["dcn_tier"] = {
                "n_hosts": int(n_hosts),
                "chips_per_host": dcn["chips_per_host"],
                "tiered_wall_s": round(t_tiered, 4),
                "bytes_dcn_per_wave": dcn["bytes_dcn_total_per_wave"],
                "flat_dcn_per_wave": dcn["flat_dcn_total_per_wave"],
                "dcn_cut_vs_flat": round(dcn["dcn_cut_vs_flat"], 4),
                "dcn_within_quarter": bool(
                    dcn["dcn_cut_vs_flat"] <= 0.25),
                "bytes_ici_per_wave": dcn["bytes_ici_per_wave"],
                "placements_match_flat": parity,
            }

            # ---- kill-one-shard recovery-time probe (the elastic
            # solver: tile layout + fail/recover state machine) ----
            es = ElasticShardedResidentSolver(
                nodes, asks_for(probe_job),
                mesh=make_two_tier_mesh(n_hosts, n_devices),
                gp=1 << max(0, (gp_need - 1).bit_length()),
                kp=1 << max(0, (count - 1).bit_length()),
                max_waves=18, pallas="off")
            b2 = [es.pack_batch(asks_for(j)) for j in jobs]
            victim = es.n_shards - 1
            lost = es.fail_shard(victim)
            t0 = time.perf_counter()
            es.solve_stream(b2[:epc])          # degraded, fast path
            t_degraded = time.perf_counter() - t0
            rc = es.reshard_counters
            rec_bytes = es.recover()
            es.reset_usage()
            t0 = time.perf_counter()
            es.solve_stream(b2[:epc])
            t_recovered = time.perf_counter() - t0
            grown = es.grow_tiles(1)
            rec["recovery_probe"] = {
                "killed_shard": int(victim),
                "lost_tiles": len(lost),
                "degraded_solve_s": round(t_degraded, 4),
                "degraded_on_fast_path": rc["degraded_solves"] >= 1,
                "recovery_s": round(rc["last_recovery_s"], 4),
                "recovery_bytes": int(rec_bytes),
                "recovered_solve_s": round(t_recovered, 4),
                "grow_tiles": grown,
                "grow_bytes_measured": rc["last_reshard_bytes"],
            }
        out["configs"].append(rec)
    out["ok"] = all(c["ici_within_bound"] for c in out["configs"])
    out["dcn_ok"] = all(
        c["dcn_tier"]["dcn_within_quarter"]
        and c["dcn_tier"]["placements_match_flat"]
        for c in out["configs"] if "dcn_tier" in c)
    if write_detail:
        with open(os.path.join(REPO, "MULTICHIP_DETAIL.json"),
                  "w") as f:
            json.dump(out, f, indent=1)
    return out


# --------------- multi-region WAN federation phase (ISSUE 13) -------

def _region_queue_sim(arrivals, regions, svc, router=None,
                      watermark=None):
    """Deterministic FIFO queue simulation shared by the multiregion
    legs.  arrivals: [(t, home_region)] ascending; each region is one
    server with fixed per-eval service time `svc` (the measured
    device rate).  With a SpilloverRouter the router picks the region
    per arrival (backlogs fed via note_ready, shed lane drained as
    capacity returns); without one every eval runs in its home region
    and `watermark` backlogs are recorded as brownouts.  Returns
    (latencies, browned_regions, completed).  A router carrying a
    WanLatencyModel charges every cross-region hop its modeled
    (seeded, jittered) WAN delay before the eval reaches the remote
    queue — spillover is never free."""
    import collections
    comp = {r: collections.deque() for r in regions}
    last = {r: 0.0 for r in regions}
    lat, browned = [], set()

    def depth(r, t):
        dq = comp[r]
        while dq and dq[0] <= t:
            dq.popleft()
        return len(dq)

    def enqueue(r, t, t_arr):
        done = max(last[r], t) + svc
        last[r] = done
        comp[r].append(done)
        lat.append(done - t_arr)

    for t, home in arrivals:
        if router is None:
            if depth(home, t) >= watermark:
                browned.add(home)
            enqueue(home, t, t)
            continue
        for r in regions:
            router.region(r).note_ready(depth(r, t))
        for ev, r in router.drain_shed():
            enqueue(r, t + router.wan_delay(ev[1], r), ev[0])
        reg, _cause = router.route((t, home), home=home)
        if reg is not None:
            enqueue(reg, t + router.wan_delay(home, reg), t)
    # park-drain: anything the router shed completes once capacity
    # returns (never dropped)
    t = max(last.values())
    for _ in range(100_000):
        if router is None or not router.shed_depth():
            break
        t += svc
        for r in regions:
            router.region(r).note_ready(depth(r, t))
        for ev, r in router.drain_shed():
            enqueue(r, t + router.wan_delay(ev[1], r), ev[0])
    return lat, browned, len(lat)


def run_multiregion(n_devices=8, n_regions=4, n_nodes=None, n_evals=16,
                    count=64, evals_per_call=8, write_detail=True):
    """Multi-region WAN federation phase (ISSUE 13).

    Two legs.  (a) WAN exchange: CrossRegionResidentSolver places the
    same eval stream as a flat ShardedResidentSolver over the union
    fleet — placements must match exactly (the hierarchical candidate
    exchange is a transport optimisation, not a semantic change) —
    and wave_traffic's wan block reports the three-tier byte model
    with the `wan_cut_vs_flat <= 1/4` acceptance figure at bench
    scale.  (b) SLO spillover: a deterministic queue simulation
    parameterised by the measured device solve rate, driving skewed
    regional load (one hot region at ~1.4x its capacity) through
    three routing policies — region-isolated (stock semantics: the
    hot region browns out), SpilloverRouter (overflow to the
    cheapest sibling at SLO), and a balanced-load reference.  The
    acceptance bar: spillover's global p99 stays within 2x the
    balanced p99 while the isolated leg browns out, with zero evals
    lost and the shed-lane accounting intact.

    Self-provisions the virtual device platform like run_multichip;
    sizes default to 50k union nodes (NOMAD_TPU_MULTIREGION_NODES
    overrides).  The record merges into MULTICHIP_DETAIL.json under
    "multiregion"."""
    import importlib
    graft = importlib.import_module("__graft_entry__")
    n_devices, n_regions = graft._ensure_devices(n_devices, n_regions)
    import random

    import jax
    import numpy as np
    from nomad_tpu.parallel.federated import CrossRegionResidentSolver
    from nomad_tpu.parallel.sharded import ShardedResidentSolver
    from nomad_tpu.server.serving import SpilloverRouter, WanLatencyModel
    from nomad_tpu.solver.tensorize import Tensorizer
    from nomad_tpu.utils.compile_cache import cache_entries

    if n_nodes is None:
        n_nodes = int(os.environ.get("NOMAD_TPU_MULTIREGION_NODES",
                                     "50000"))
    per_region = n_nodes // n_regions
    nodes = make_nodes(per_region * n_regions)
    region_nodes = [nodes[r * per_region:(r + 1) * per_region]
                    for r in range(n_regions)]
    probe_job = make_job(2, 0, count)
    gp_need = len({Tensorizer.ask_signature(a)
                   for a in asks_for(probe_job)})
    gp = 1 << max(0, (gp_need - 1).bit_length())
    kp = 1 << max(0, (count - 1).bit_length())
    epc = min(evals_per_call, n_evals)
    NB = -(-n_evals // epc)
    out = {"phase": "multiregion", "n_devices": int(n_devices),
           "n_regions": int(n_regions), "skipped": False,
           "backend": jax.default_backend()}

    # ---- WAN leg: cross-region scheduling vs the flat union mesh ---
    cache0 = cache_entries()
    cr = CrossRegionResidentSolver(
        region_nodes, asks_for(probe_job), n_devices=n_devices,
        gp=gp, kp=kp, max_waves=18, pallas="off")
    jobs = [make_job(2, e, count) for e in range(n_evals)]
    batches = [cr.pack_batch(asks_for(j)) for j in jobs]
    assert all(pb is not None for pb in batches)
    t_wan = None
    for _round in range(2):                      # round 0 compiles
        cr.reset_usage()
        t0 = time.perf_counter()
        outs = [cr.solve_stream_async(batches[b * epc:(b + 1) * epc])
                for b in range(NB)]
        jax.block_until_ready(outs[-1])
        t_wan = time.perf_counter() - t0
    cache_rep = _cache_report(cache0)

    rs = ShardedResidentSolver(nodes, asks_for(probe_job),
                               n_devices=n_devices, gp=gp, kp=kp,
                               max_waves=18, pallas="off")
    bf = [rs.pack_batch(asks_for(j)) for j in jobs]
    t_flat = None
    for _round in range(2):
        rs.reset_usage()
        t0 = time.perf_counter()
        outs = [rs.solve_stream_async(bf[b * epc:(b + 1) * epc])
                for b in range(NB)]
        jax.block_until_ready(outs[-1])
        t_flat = time.perf_counter() - t0
    # placement parity spot check: the WAN exchange must be invisible
    cr.reset_usage()
    rs.reset_usage()
    c1, o1, _, st1 = cr.solve_stream(batches[:epc])
    c2, o2, _, st2 = rs.solve_stream(bf[:epc])
    parity = bool(np.array_equal(o1, o2)
                  and np.array_equal(st1, st2)
                  and np.array_equal(np.where(o1, c1, -1),
                                     np.where(o2, c2, -1)))
    wt = cr.wave_traffic(batches[:epc])
    wan = wt["wan"]
    measured = wt["measured"]
    out["wan"] = {
        "n_nodes": int(n_nodes),
        "np_padded": int(cr.template.avail.shape[0]),
        "shards_per_region": wan["shards_per_region"],
        "wan_resident_s": round(t_wan, 4),
        "flat_resident_s": round(t_flat, 4),
        "placements_match_flat": parity,
        "bytes_wan_per_wave": wan["bytes_wan_total_per_wave"],
        "flat_wan_per_wave": wan["flat_wan_total_per_wave"],
        "wan_cut_vs_flat": round(wan["wan_cut_vs_flat"], 4),
        "wan_within_quarter": bool(wan["wan_cut_vs_flat"] <= 0.25),
        "model": wan,
        "measured": measured,
        "compile_cache": cache_rep,
    }

    # ---- spillover leg: skewed load through three routing policies -
    # measured per-eval device rate parameterises the queue sim; the
    # p99 RATIOS are scale-free (all times are multiples of svc), so
    # the acceptance figure is deterministic under the fixed seed
    svc = max(t_wan / max(n_evals, 1), 1e-6)
    regions = [f"r{i}" for i in range(n_regions)]
    rng = random.Random(13)
    n_arr = 400
    lam = 2.0 / svc                      # total load = 50% of fleet
    t_a, arrivals = 0.0, []
    for _ in range(n_arr):
        t_a += rng.expovariate(lam)
        hot = rng.random() < 0.7         # ~1.4x the hot region's rate
        arrivals.append((t_a, regions[0] if hot
                         else regions[1 + rng.randrange(
                             n_regions - 1)]))
    balanced = [(t, regions[i % n_regions])
                for i, (t, _h) in enumerate(arrivals)]
    mp_small = 64                        # smoke-scale watermark
    lat_iso, browned, done_iso = _region_queue_sim(
        arrivals, regions, svc, watermark=int(0.75 * mp_small))

    # modeled WAN latency (ISSUE 14): every cross-region hop costs a
    # per-pair base (here 0.5 svc — the scale-free knob) with seeded
    # jitter; routing math subtracts the jitter-free expectation from
    # the SLO budget so remote capacity is never judged free
    wan_base = 0.5 * svc

    def _wan_model():
        return WanLatencyModel(default_s=wan_base, jitter=0.25)

    def _router():
        r = SpilloverRouter(
            regions={name: 1.0 + 0.1 * i
                     for i, name in enumerate(regions)},
            overrides={"slo_budget_s": 2.5 * svc, "spill_margin": 1.0,
                       "max_pending": mp_small},
            wan_model=_wan_model())
        for name in regions:
            for b in (1, 2, 4, 8, 16, 32, 64):
                r.note_solve(name, b, b * svc)
        return r

    router = _router()
    lat_sp, _b, done_sp = _region_queue_sim(arrivals, regions, svc,
                                            router=router)
    router_bal = _router()
    lat_bal, _b, done_bal = _region_queue_sim(balanced, regions, svc,
                                              router=router_bal)
    p99_iso = pct(sorted(lat_iso), 0.99)
    p99_sp = pct(sorted(lat_sp), 0.99)
    p99_bal = pct(sorted(lat_bal), 0.99)
    stats = router.stats()
    out["spillover"] = {
        "n_arrivals": n_arr,
        "svc_per_eval_s": round(svc, 6),
        "hot_region_share": 0.7,
        "isolated_browned_regions": sorted(browned),
        "p99_isolated_s": round(p99_iso, 4),
        "p99_spillover_s": round(p99_sp, 4),
        "p99_balanced_s": round(p99_bal, 4),
        "p99_vs_balanced": round(p99_sp / max(p99_bal, 1e-9), 3),
        "evals_lost": (n_arr - done_sp) + (n_arr - done_iso)
        + (n_arr - done_bal),
        "shed_lane_depth_end": router.shed_depth(),
        "routed": stats["routed"],
        "wan": {"base_s": round(wan_base, 6),
                "base_vs_svc": 0.5, "jitter": 0.25,
                **stats.get("wan", {})},
        "shed_accounting_intact": (
            stats["routed"]["shed"] == stats["routed"]["readmitted"]
            and router.shed_depth() == 0),
        "spill_ok": bool(p99_sp <= 2 * p99_bal and browned
                         and done_sp == n_arr),
    }
    out["ok"] = bool(out["wan"]["wan_within_quarter"] and parity
                     and out["spillover"]["spill_ok"]
                     and out["spillover"]["evals_lost"] == 0
                     and out["spillover"]["shed_accounting_intact"])
    if write_detail:
        path = os.path.join(REPO, "MULTICHIP_DETAIL.json")
        detail = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    detail = json.load(f)
            except (OSError, json.JSONDecodeError):
                detail = {}
        detail["multiregion"] = out
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
    return out


# ------------------- chaos storm phase (ISSUE 14) -------------------

def run_chaos(n_devices=8, n_regions=4, write_detail=True, seed=14):
    """Chaos plane phase (ISSUE 14): a seeded compound fault storm —
    shard kills + region partitions + gossip flaps + stuck/slow/
    poisoned device solves — replayed through the real recovery hooks
    at config-3 load, with the invariant harness running continuously.

    Three sub-records:

      * ``watchdog`` — the acceptance failover arc: a stuck device
        solve (injected sleep past the deadline) answers from the
        bit-identical host twin with PLACEMENT-IDENTICAL results,
        quarantines the device, keeps answering from the twin while
        the backoff pends, and recovers to the device fast path on a
        clean probe — all visible in the mesh event log;
      * ``corruption`` — a delta-row corruption (device planes diverge
        from the raft-fed host template) is caught by the plane
        checksum invariant and healed by a clean re-apply;
      * ``storm`` — a fault-free leg vs the storm leg over identical
        eval streams: per-step latencies (p50/p99), zero lost evals,
        zero invariant violations, post-storm placements bit-identical
        to the fault-free reference, recovery times, and the
        watchdog-lane fast-path retention.

    Acceptance: zero violations, zero lost evals, storm p99 <= 3x the
    fault-free p99, and the watchdog failover demonstrated.  Merges
    into BENCH_DETAIL.json under "chaos"."""
    import importlib
    graft = importlib.import_module("__graft_entry__")
    n_devices, n_regions = graft._ensure_devices(n_devices, n_regions)
    import numpy as np
    from nomad_tpu import mock
    from nomad_tpu.chaos import (ChaosSupervisor, FaultPlan,
                                 InvariantHarness, global_injections)
    from nomad_tpu.parallel.federated import CrossRegionResidentSolver
    from nomad_tpu.parallel.sharded import ElasticMeshSupervisor
    from nomad_tpu.server.eval_broker import EvalBroker
    from nomad_tpu.server.serving import AdmissionController
    from nomad_tpu.solver.solve import _run_kernel
    from nomad_tpu.solver.tensorize import ClusterDelta, Tensorizer
    from nomad_tpu.solver.watchdog import global_watchdog
    from nomad_tpu.utils.metrics import global_metrics as _m
    from nomad_tpu.utils.tracing import global_mesh_events

    p3 = CONFIGS[3]
    n_nodes = int(os.environ.get("NOMAD_TPU_CHAOS_NODES",
                                 p3["n_nodes"]))
    resident = int(os.environ.get(
        "NOMAD_TPU_CHAOS_RESIDENT",
        p3["resident"] * n_nodes // p3["n_nodes"]))
    count = p3["count"]
    horizon = int(os.environ.get("NOMAD_TPU_CHAOS_HORIZON", "36"))
    per_region = n_nodes // n_regions
    nodes = make_nodes(per_region * n_regions)
    region_nodes = [nodes[r * per_region:(r + 1) * per_region]
                    for r in range(n_regions)]
    probe_job = make_job(3, 0, count)
    gp_need = len({Tensorizer.ask_signature(a)
                   for a in asks_for(probe_job)})
    gp = 1 << max(0, (gp_need - 1).bit_length())
    kp = 1 << max(0, (count - 1).bit_length())
    cr = CrossRegionResidentSolver(
        region_nodes, asks_for(probe_job), n_devices=n_devices,
        gp=gp, kp=kp, max_waves=18, pallas="off")
    used0 = resident_used0(cr.template, per_region * n_regions,
                           resident)
    msup = ElasticMeshSupervisor(cr.solver)
    msup.register_host("host-r1", 1)
    jobs = [make_job(3, e, count) for e in range(8)]
    batches = [cr.pack_batch(asks_for(j)) for j in jobs]
    # the watchdog device-dispatch lane: a standalone full pack (node
    # planes included — resident batches carry only the eval tensors)
    # over a modest node subset, so the host twin answers fast when
    # the watchdog fails over
    pb_wd = Tensorizer().pack(nodes[:256], asks_for(jobs[0]))
    import jax
    out = {"phase": "chaos", "seed": int(seed),
           "n_nodes": int(per_region * n_regions),
           "n_regions": int(n_regions), "resident": int(resident),
           "horizon": int(horizon),
           "backend": jax.default_backend()}

    # the storm schedule is generated up front (it is the experiment's
    # seed-addressable identity), which also lets the warmup below
    # compile every degraded-width variant the storm will actually
    # drive — the storm leg's p99 then measures fault HANDLING
    # (re-ship, failover, rebuild), not first-call compilation
    rates = {"shard_kill": 0.06, "region_kill": 0.06,
             "gossip_flap": 0.08, "stuck_solve": 0.05,
             "slow_solve": 0.08, "poison_solve": 0.05}
    plan = FaultPlan.generate(seed, horizon, rates,
                              shards=cr.solver.n_shards,
                              regions=cr.region_names,
                              members=["host-r1"])

    cr.reset_usage(used0=used0)
    cr.solve_stream([batches[0]])
    warm_kills = [("shard", 1)]         # the gossip-flap member's shard
    for ev in plan.events:
        if ev.kind == "shard_kill":
            warm_kills.append(
                ("shard", int(ev.target or 0) % cr.solver.n_shards))
        elif ev.kind == "region_kill":
            warm_kills.append(("region", ev.target))
    for wkind, wtgt in dict.fromkeys(warm_kills):
        if wkind == "shard":
            cr.solver.fail_shard(wtgt)
        else:
            cr.fail_region_shard(wtgt)
        cr.reset_usage(used0=used0)
        cr.solve_stream([batches[0]])
        cr.solver.recover()
        cr.reset_usage(used0=used0)
        cr.solve_stream([batches[0]])
    _run_kernel(pb_wd, host_mode="never")

    # ---- watchdog failover arc (the acceptance demo) ----
    deadline = float(os.environ.get("NOMAD_TPU_SOLVE_DEADLINE_S",
                                    "0.5"))
    global_watchdog.deadline_s = deadline
    global_watchdog.quarantined = False
    global_watchdog._failures = 0
    base_choice = np.asarray(
        _run_kernel(pb_wd, host_mode="never").choice)
    global_injections.arm("device_solve", "sleep", budget=1,
                          sleep_s=4.0 * deadline)
    t0 = time.perf_counter()
    stuck = np.asarray(_run_kernel(pb_wd, host_mode="never").choice)
    failover_s = time.perf_counter() - t0
    quarantined = bool(global_watchdog.quarantined)
    twin = np.asarray(_run_kernel(pb_wd, host_mode="never").choice)
    global_watchdog._probe_at = 0.0            # backoff elapsed
    probed = np.asarray(_run_kernel(pb_wd, host_mode="never").choice)
    out["watchdog"] = {
        "deadline_s": deadline,
        "failover_s": round(failover_s, 4),
        "failover_placements_identical": bool(
            np.array_equal(stuck, base_choice)),
        "quarantined_after_failover": quarantined,
        "quarantine_twin_identical": bool(
            np.array_equal(twin, base_choice)),
        "recovered_to_device": bool(not global_watchdog.quarantined),
        "probe_placements_identical": bool(
            np.array_equal(probed, base_choice)),
        "failover_in_event_log": bool(global_mesh_events.events(
            kind="watchdog.failover", limit=4096)),
        "recovery_in_event_log": bool(global_mesh_events.events(
            kind="watchdog.recovered", limit=4096)),
    }
    out["watchdog"]["ok"] = all(
        v for k, v in out["watchdog"].items()
        if isinstance(v, bool))

    # ---- delta-row corruption: detected, then healed ----
    hc = InvariantHarness()
    clean_before = hc.check_plane_checksums(cr.solver)
    victim = nodes[7]
    victim.node_resources.cpu += 1
    victim.compute_class()
    d = ClusterDelta()
    d.upsert_nodes.append(victim)
    global_injections.arm("delta_row", "mutate", budget=1, rows=2)
    corr_path = cr.apply_delta(d)
    detected = not hc.check_plane_checksums(cr.solver)
    d2 = ClusterDelta()
    d2.upsert_nodes.append(victim)         # clean re-apply heals
    cr.apply_delta(d2)
    healed = InvariantHarness().check_plane_checksums(cr.solver)
    out["corruption"] = {"apply_path": corr_path,
                         "clean_before": bool(clean_before),
                         "detected": bool(detected),
                         "healed_by_reapply": bool(healed)}

    # ---- fault-free leg vs the compound storm leg ----
    # each step serves SPS fleet batches + the watchdog lane + an
    # eval-broker burst: the per-step cost a client sees at config-3
    # load, against which a transition's one-time re-ship/failover
    # cost amortizes (exactly how a real serving tier absorbs it)
    SPS = 4                             # fleet solves per step

    def run_leg(supervisor):
        broker = EvalBroker(initial_nack_delay_s=0.01)
        broker.set_enabled(True)
        adm = AdmissionController(max_pending=4096,
                                  protect_priority=101,
                                  brownout_high=0.9,
                                  brownout_low=0.5,
                                  brownout_after_s=0.001,
                                  ns_rate=1e9, ns_burst=1e9)
        harness = InvariantHarness()
        dbg = os.environ.get("NOMAD_TPU_CHAOS_DEBUG")
        lat, recovery_s = [], []
        t_kill = None
        for step in range(horizon):
            t0 = time.perf_counter()
            if supervisor is not None:
                for e in supervisor.advance(step):
                    if e.kind in ("shard_kill", "region_kill"):
                        t_kill = time.perf_counter()
            t_adv = time.perf_counter()
            for i in range(SPS):
                ev = mock.eval_(job_id=f"job-{step}-{i}")
                harness.note_enqueued(ev.id)
                if adm.offer(ev, broker.ready_count()):
                    broker.enqueue(ev)
                else:
                    harness.note_outcome(ev.id, "shed")
            t_ev = time.perf_counter()
            for b in range(SPS):
                pb = batches[(step * SPS + b) % len(batches)]
                cr.reset_usage(used0=used0)
                choice, ok, _sc, _st = cr.solve_stream([pb])
            t_solve = time.perf_counter()
            res = _run_kernel(pb_wd, host_mode="never")
            t_lane = time.perf_counter()
            wd_choice = np.asarray(res.choice)
            for pi in range(min(4, pb_wd.n_place)):
                harness.note_placement(
                    f"s{step}-p{pi}", str(int(wd_choice[pi, 0])))
            while True:
                got, tok = broker.dequeue(["service"], 0.0)
                if got is None:
                    break
                broker.ack(got.id, tok)
                harness.note_outcome(got.id, "acked")
            if supervisor is not None and t_kill is not None \
                    and cr.mesh_state == "healthy":
                # the storm (or a gossip rejoin) recovered the mesh
                recovery_s.append(time.perf_counter() - t_kill)
                t_kill = None
            t_drain = time.perf_counter()
            lat.append(time.perf_counter() - t0)
            # the continuously-running invariant harness
            harness.check_eval_conservation(broker)
            harness.check_no_double_placement()
            harness.check_plane_checksums(cr.solver)
            harness.check_shed_accounting(admission=adm)
            if dbg:
                print(f"step {step:2d} total {lat[-1]:.3f} "
                      f"adv {t_adv - t0:.3f} "
                      f"evq {t_ev - t_adv:.3f} "
                      f"solve {t_solve - t_ev:.3f} "
                      f"lane {t_lane - t_solve:.3f} "
                      f"drain {t_drain - t_lane:.3f} "
                      f"chk {time.perf_counter() - t_drain:.3f}",
                      file=sys.stderr)
        if cr.mesh_state == "degraded":       # final quiesce
            t0 = time.perf_counter()
            cr.solver.recover()
            recovery_s.append(time.perf_counter()
                              - (t_kill or t0))
        harness.check_plane_checksums(cr.solver)
        cr.reset_usage(used0=used0)
        c, o, _s, st = cr.solve_stream([batches[0]])
        final = (np.where(o, c, -1).copy(), np.asarray(st).copy())
        return lat, harness, recovery_s, final

    c0 = _m.dump()["counters"]
    wd_host0 = (c0.get("watchdog.host_failover", 0)
                + c0.get("watchdog.host_quarantine", 0))
    lat_ff, h_ff, _rec, final_ff = run_leg(None)
    sup = ChaosSupervisor(plan, federated=cr, mesh_supervisor=msup,
                          injections=global_injections,
                          watchdog_deadline_s=deadline)
    lat_st, h_st, recovery_s, final_st = run_leg(sup)
    c1 = _m.dump()["counters"]
    wd_host1 = (c1.get("watchdog.host_failover", 0)
                + c1.get("watchdog.host_quarantine", 0))
    host_answers = wd_host1 - wd_host0
    p99_ff = pct(sorted(lat_ff), 0.99)
    p99_st = pct(sorted(lat_st), 0.99)
    rep = sup.report()
    out["storm"] = {
        "plan": rep,
        "evals_per_step": SPS,
        "solves_per_step": SPS,
        "p50_fault_free_s": round(pct(sorted(lat_ff), 0.50), 4),
        "p99_fault_free_s": round(p99_ff, 4),
        "p50_storm_s": round(pct(sorted(lat_st), 0.50), 4),
        "p99_storm_s": round(p99_st, 4),
        "p99_ratio": round(p99_st / max(p99_ff, 1e-9), 3),
        "evals_lost": 0 if (h_ff.ok and h_st.ok) else -1,
        "invariants_fault_free": h_ff.report(),
        "invariants_storm": h_st.report(),
        "recovery_s": [round(r, 4) for r in recovery_s],
        "step_lat_fault_free_s": [round(v, 3) for v in lat_ff],
        "step_lat_storm_s": [round(v, 3) for v in lat_st],
        "watchdog_host_answers": int(host_answers),
        "fast_path_retention": round(
            1.0 - host_answers / (2.0 * horizon), 4),
        "post_storm_placements_match_fault_free": bool(
            np.array_equal(final_st[0], final_ff[0])
            and np.array_equal(final_st[1], final_ff[1])),
        "chaos_events_logged": len(global_mesh_events.events(
            limit=4096, kind=None)),
    }
    global_injections.reset()
    global_watchdog.deadline_s = None
    out["ok"] = bool(
        out["watchdog"]["ok"]
        and out["corruption"]["detected"]
        and out["corruption"]["healed_by_reapply"]
        and h_ff.ok and h_st.ok
        and out["storm"]["p99_ratio"] <= 3.0
        and out["storm"]["post_storm_placements_match_fault_free"])
    if write_detail:
        path = os.path.join(REPO, "BENCH_DETAIL.json")
        detail = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    detail = json.load(f)
            except (OSError, json.JSONDecodeError):
                detail = {}
        detail["chaos"] = out
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
    return out


# ---------------- open-loop serving phase (ISSUE 6) -----------------

def poisson_arrivals(rate, duration_s, rng):
    """Memoryless open-loop arrivals: [(t_offset, namespace), ...]."""
    t, out = 0.0, []
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            return out
        out.append((t, "default"))


def trace_arrivals(rate, duration_s, rng, n_tenants=6,
                   mean_burst=8.0):
    """Tesserae-shaped trace family (arxiv 2508.04953): DL-cluster
    scheduler workloads are bursty and multi-tenant.  Per tenant, an
    ON/OFF burst train — bursts arrive Poisson, each carrying a
    lognormal-sized run of back-to-back evals — with one hot tenant
    holding ~3x the share of the rest (the flapping tenant the
    admission fairness buckets exist for).  Mean rate ~= `rate`."""
    shares = [3.0] + [1.0] * (n_tenants - 1)
    total = sum(shares)
    out = []
    for ti, share in enumerate(shares):
        tenant_rate = rate * share / total
        burst_rate = tenant_rate / mean_burst
        t = 0.0
        while True:
            t += rng.expovariate(burst_rate)
            if t >= duration_s:
                break
            n = max(1, int(rng.lognormvariate(1.7, 0.8)))
            for k in range(n):
                out.append((min(duration_s - 1e-6, t + k * 1e-4),
                            f"tenant-{ti}"))
    out.sort()
    return out


class _ServingHarness:
    """The serving tier wired end to end for the bench: a real
    EvalBroker + BlockedEvals + AdmissionController feeding the real
    ResidentSolver — the production worker loop's shape (adaptive
    dequeue sizing, bypass lane, pause-nack, shed/readmit) without the
    scheduler/raft plane around it, so the measured number is the
    broker -> solver serving path itself."""

    def __init__(self, rs, template_ask, count, policy, slo_s,
                 max_batch, fixed_batch, max_pending):
        import threading

        from nomad_tpu.server.blocked_evals import BlockedEvals
        from nomad_tpu.server.eval_broker import EvalBroker
        from nomad_tpu.server.serving import (AdmissionController,
                                              BatchController,
                                              EwmaSolveModel)
        self.rs = rs
        self.template_ask = template_ask
        self.count = count
        self.policy = policy            # "adaptive" | "fixed"
        self.fixed_batch = fixed_batch
        self.max_batch = max_batch
        self.broker = EvalBroker(nack_delay_s=60.0)
        self.broker.set_enabled(True)
        self.blocked = BlockedEvals(self.broker)
        self.blocked.set_enabled(True)
        self.model = EwmaSolveModel()
        self.controller = BatchController(self.model, slo_budget_s=slo_s,
                                          max_batch=max_batch)
        self.admission = AdmissionController(
            max_pending=max_pending, protect_priority=80,
            ns_rate=max(64.0, max_pending / 2.0),
            ns_burst=max(128.0, float(max_pending)),
            brownout_after_s=0.25)
        self.arrival_t = {}             # eval id -> arrival perf_counter
        self.readmitted = set()
        self.warmup_ids = set()         # excluded from the percentiles
        self.lat_s = []                 # direct-admitted completions
        self.lat_express_s = []         # bypass-lane completions
        self.completed = 0
        self.offered = 0
        self.batch_sizes = []
        self.stop = threading.Event()
        self._seq = 0

    # ---- ingress (arrival thread)
    def ingress(self, ev):
        self.offered += 1
        self.arrival_t[ev.id] = time.perf_counter()
        if self.admission.offer(ev, self.broker.ready_count()):
            self.broker.enqueue(ev)
        else:
            self.blocked.shed(ev)

    # ---- the serving loop (worker analog)
    def serve_loop(self):
        broker = self.broker
        while not self.stop.is_set():
            if self.policy == "adaptive":
                target = self.controller.target_batch(
                    broker.ready_count(), broker.oldest_ready_age())
            else:
                target = self.fixed_batch
            batch = broker.dequeue_batch(["service"], target, 0.002)
            if not batch:
                self._readmit()
                continue
            t0 = time.perf_counter()
            for ev, tok in batch:
                broker.pause_nack_timeout(ev.id, tok)
            express = [(e, t) for e, t in batch if e.priority >= 80]
            bulk = [(e, t) for e, t in batch if e.priority < 80]
            for group in (express, bulk):
                if group:
                    self._solve([e for e, _ in group])
            now = time.perf_counter()
            for ev, tok in batch:
                broker.ack(ev.id, tok)
                t_arr = self.arrival_t.pop(ev.id, None)
                if (t_arr is None or ev.id in self.readmitted
                        or ev.id in self.warmup_ids):
                    continue
                if ev.priority >= 80:
                    self.lat_express_s.append(now - t_arr)
                else:
                    self.lat_s.append(now - t_arr)
            self.completed += len(batch)
            self.batch_sizes.append(len(batch))
            self.model.observe(len(batch), now - t0)
            self._readmit()

    def _solve(self, evs):
        # every eval is one config-2-shaped placement ask; identical
        # signatures merge to a single packed row with summed count
        # (the columnar coalescing payoff), solved in ONE device call
        asks = [self.template_ask] * len(evs)
        masks, keys = self.rs.merge_asks(asks)
        pb = self.rs.pack_batch(masks)
        self._seq += 1
        self.rs.solve_stream([pb], seeds=[self._seq])

    def _readmit(self):
        quota = self.admission.readmit_quota(
            self.broker.ready_count(), batch=self.max_batch)
        if quota > 0:
            for ev in self.blocked.pop_shed(quota):
                self.readmitted.add(ev.id)
                self.broker.enqueue(ev)

    # ---- accounting
    def leftovers(self):
        st = self.broker.stats()
        return (st["total_ready"] + st["total_unacked"]
                + st["total_waiting"] + st["total_blocked"]
                + self.blocked.shed_count())


def _run_open_loop_leg(rs, template_ask, count, policy, arrivals,
                       duration_s, slo_s, max_batch, fixed_batch,
                       max_pending, used0, warmup_s=0.5,
                       express_every_s=0.0):
    """Drive one (policy, arrival process) leg and return its record."""
    import gc
    import threading

    from nomad_tpu.structs import Evaluation

    gc.collect()          # a mid-leg GC hiccup lands straight in p99
    rs.reset_usage(used0=used0)
    h = _ServingHarness(rs, template_ask, count, policy, slo_s,
                        max_batch, fixed_batch, max_pending)
    loop = threading.Thread(target=h.serve_loop, daemon=True)
    loop.start()
    # bypass-lane probes (the config-1 interactive class) ride along at
    # a fixed low rate when requested
    if express_every_s:
        express = [(t, "_express") for t in
                   _frange(express_every_s, duration_s, express_every_s)]
        arrivals = sorted(arrivals + express)
    t_start = time.perf_counter()
    i, n = 0, len(arrivals)
    while i < n:
        now = time.perf_counter() - t_start
        while i < n and arrivals[i][0] <= now:
            t_off, ns = arrivals[i]
            i += 1
            if ns == "_express":
                ev = Evaluation(job_id=f"ol-x-{i}", priority=90)
            else:
                ev = Evaluation(job_id=f"ol-{i}", namespace=ns,
                                priority=50)
            if t_off < warmup_s:
                # warmup window: served and counted for throughput, but
                # excluded from the percentiles (the EWMA model trains
                # during it)
                h.warmup_ids.add(ev.id)
            h.ingress(ev)
        if i < n:
            time.sleep(min(0.001, max(0.0, arrivals[i][0]
                                      - (time.perf_counter() - t_start))))
    # grace drain: overload legs stay bounded by admission, so this
    # terminates fast either way
    t_grace = time.perf_counter()
    while (time.perf_counter() - t_grace < 2.0
           and h.broker.stats()["total_ready"] > 0):
        time.sleep(0.01)
    h.stop.set()
    loop.join(timeout=5.0)
    elapsed = time.perf_counter() - t_start
    admitted = h.admission.stats()
    shed_left = h.blocked.shed_count()
    lost = h.offered - h.completed - h.leftovers()
    lat = latency_summary(h.lat_s)
    bs = sorted(h.batch_sizes)
    return {
        "policy": policy,
        "offered": h.offered,
        "completed": h.completed,
        "elapsed_s": round(elapsed, 3),
        "completed_per_sec": round(h.completed / max(elapsed, 1e-9), 1),
        "offered_rate_per_sec": round(h.offered / max(duration_s, 1e-9),
                                      1),
        "p50_ms": lat["p50_ms"], "p99_ms": lat["p99_ms"],
        "interactive": (latency_summary(h.lat_express_s)
                        if h.lat_express_s else None),
        "shed": admitted["shed"],
        "shed_remaining": shed_left,
        "readmitted": len(h.readmitted),
        "brownouts_entered": admitted["brownouts_entered"],
        "lost": lost,
        "batch_size_p50": pct([float(x) for x in bs], 0.5),
        "batch_size_p99": pct([float(x) for x in bs], 0.99),
    }


def _frange(start, stop, step):
    out = []
    t = start
    while t < stop:
        out.append(t)
        t += step
    return out


def run_open_loop(n_nodes=2048, count=4, max_batch=128, fixed_batch=8,
                  slo_ms=50.0, duration_s=4.0, resident=5000,
                  loads=(0.5, 0.75, 1.0, 1.5, 2.0), seed=7,
                  write_detail=True):
    """Open-loop serving-tier phase (ISSUE 6 acceptance).

    Measures the broker -> resident-solver serving path under
    Poisson/trace-driven arrivals at load multiples of each policy's
    MEASURED capacity (saturation probe), reporting sustained evals/sec
    at p99 < slo_ms plus the saturation/brownout curve:

      * adaptive: BatchController-sized dequeues (SLO-budget close
        rule, EWMA solve model, drain mode) + admission control
      * fixed:    the pre-serving-tier baseline — fixed-size dequeue
        (`server.batch_size` analog) with the same admission bound

    The acceptance figure `adaptive_vs_fixed_sustained` compares the
    highest sustained throughput each policy achieves while holding
    p99 < slo_ms across its own load sweep.  CPU-backend numbers are
    acceptable per the issue; the per-dispatch overhead the adaptive
    batcher amortizes exists on every backend (and grows with the
    tunneled-transport round trip)."""
    import random

    from nomad_tpu.solver.resident import ResidentSolver
    from nomad_tpu.solver.tensorize import Tensorizer

    rng = random.Random(seed)
    slo_s = slo_ms / 1000.0
    nodes = make_nodes(n_nodes)
    probe_job = make_job(2, 0, count)
    template_ask = asks_for(probe_job)[0]
    gp_need = len({Tensorizer.ask_signature(a)
                   for a in asks_for(probe_job)})
    t0 = time.perf_counter()
    rs = ResidentSolver(nodes, asks_for(probe_job),
                        gp=1 << max(0, (gp_need - 1).bit_length()),
                        kp=1 << max(0, (count * max_batch - 1)
                                    .bit_length()),
                        max_waves=18)
    used0 = resident_used0(rs.template, n_nodes, resident)
    rs.reset_usage(used0=used0)
    # warm every pow2 group_count_hint bucket the sweep can hit: batch
    # sizes vary, padded shapes do not — no compiles in the timed legs
    import dataclasses
    k = 1
    while k <= max_batch:
        asks = [dataclasses.replace(template_ask, count=count)] * k
        masks, keys = rs.merge_asks(asks)
        rs.solve_stream([rs.pack_batch(masks)], seeds=[1])
        k <<= 1
    rs.reset_usage(used0=used0)
    startup_s = time.perf_counter() - t0

    # ---- capacity probe per policy: saturating arrivals, completed/s.
    # Peak drain throughput is a rho=1 operating point — open-loop
    # arrivals AT it queue without bound by Little's law — so the
    # sweep's "1.0x capacity" is 0.9x the measured peak, the classic
    # sustainable-utilization derating.
    def capacity(policy):
        import gc
        rate = 60000.0
        peaks = []
        for trial in range(3):
            gc.collect()
            probe = poisson_arrivals(rate, 1.5,
                                     random.Random(seed + 1 + trial))
            rec = _run_open_loop_leg(
                rs, template_ask, count, policy, probe, 1.5, slo_s,
                max_batch, fixed_batch, max_pending=1 << 30,
                used0=used0, warmup_s=0.25)
            peaks.append(rec["completed_per_sec"])
        return round(0.9 * statistics.median(peaks), 1)

    cap = {p: capacity(p) for p in ("adaptive", "fixed")}
    sys.stderr.write(f"open-loop capacity: adaptive={cap['adaptive']}"
                     f" fixed={cap['fixed']} evals/s\n")

    out = {"phase": "open_loop", "n_nodes": n_nodes, "count": count,
           "slo_ms": slo_ms, "max_batch": max_batch,
           "fixed_batch": fixed_batch, "duration_s": duration_s,
           "startup_s": round(startup_s, 2),
           "capacity_evals_per_sec": cap, "sweep": [], "trace": None}

    sustained = {}
    for policy in ("adaptive", "fixed"):
        # bounded ingress worth ~2 SLO budgets of service at capacity:
        # the queue the admission controller allows is the p99 the
        # admitted traffic pays at saturation
        max_pending = max(64, int(cap[policy] * slo_s * 2))
        best = 0.0
        for load in loads:
            rate = cap[policy] * load
            arrivals = poisson_arrivals(rate, duration_s,
                                        random.Random(seed + 10))
            rec = _run_open_loop_leg(
                rs, template_ask, count, policy, arrivals, duration_s,
                slo_s, max_batch, fixed_batch, max_pending, used0,
                express_every_s=0.05)
            rec.update({"load": load, "arrival": "poisson",
                        "rate_per_sec": round(rate, 1),
                        "max_pending": max_pending})
            out["sweep"].append(rec)
            if rec["p99_ms"] < slo_ms and rec["lost"] == 0:
                best = max(best, rec["completed_per_sec"])
            sys.stderr.write(
                f"open-loop {policy} load={load}: "
                f"{rec['completed_per_sec']}/s p99={rec['p99_ms']}ms "
                f"shed={rec['shed']} lost={rec['lost']}\n")
        sustained[policy] = best

    # ---- Tesserae-shaped trace leg at 1.0x (adaptive): bursty
    # multi-tenant arrivals exercising the fairness buckets
    trace = trace_arrivals(cap["adaptive"], duration_s,
                           random.Random(seed + 20))
    max_pending = max(64, int(cap["adaptive"] * slo_s * 2))
    rec = _run_open_loop_leg(
        rs, template_ask, count, "adaptive", trace, duration_s, slo_s,
        max_batch, fixed_batch, max_pending, used0,
        express_every_s=0.05)
    rec.update({"load": 1.0, "arrival": "tesserae-trace",
                "max_pending": max_pending})
    out["trace"] = rec

    ratio = (sustained["adaptive"] / sustained["fixed"]
             if sustained["fixed"] else float("inf"))
    two_x = [r for r in out["sweep"]
             if r["policy"] == "adaptive" and r["load"] == 2.0]
    out["sustained_at_slo_evals_per_sec"] = sustained
    out["adaptive_vs_fixed_sustained"] = round(ratio, 2)
    out["acceptance"] = {
        "adaptive_ge_1_3x_fixed_at_slo": ratio >= 1.3,
        "overload_2x_bounded_p99_ms": (two_x[0]["p99_ms"]
                                       if two_x else None),
        "overload_2x_shed": two_x[0]["shed"] if two_x else None,
        "overload_2x_zero_lost": (two_x[0]["lost"] == 0
                                  if two_x else None),
        "overload_2x_brownouts": (two_x[0]["brownouts_entered"]
                                  if two_x else None),
    }
    out["ok"] = bool(out["acceptance"]["adaptive_ge_1_3x_fixed_at_slo"]
                     and out["acceptance"]["overload_2x_zero_lost"])
    if write_detail:
        # merge into BENCH_DETAIL.json preserving the other phases
        path = os.path.join(REPO, "BENCH_DETAIL.json")
        try:
            with open(path) as f:
                detail = json.load(f)
        except (OSError, json.JSONDecodeError):
            detail = {}
        detail["open_loop"] = out
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
    return out


# ---------------- scale-out serving phase (ISSUE 17) ----------------

#: PR 17's recorded BENCH_DETAIL.json scaleout best (4x4 fused,
#: serialized rounds) — the fixed reference the ISSUE 19 ">= 3x"
#: acceptance names.  The regenerated detail keeps a same-machine
#: serialized reference leg alongside, so both ratios stay honest.
PR17_RECORDED_BEST = 3768.0

#: PR 19's recorded BENCH_DETAIL.json scaleout best (2x2 pipelined
#: rounds): throughput and the leader-serial `device` stage wall over
#: the 2s measured window.  ISSUE 20's lane acceptance binds on the
#: recorded device stage — the lane sweep's best leg must cut it by
#: >= 30% (serial scan depth B -> B/L shows up exactly there).
PR19_RECORDED_BEST = 24409.7
PR19_RECORDED_DEVICE_S = 1.721
#: the same leg normalized per eval: device stage seconds over the 2s
#: window's completed count (24409.7/s x 2s) — the lane acceptance
#: compares device time PER EVAL, which survives window-length and
#: machine-speed drift where the raw stage wall does not
PR19_RECORDED_DEVICE_US_PER_EVAL = round(
    PR19_RECORDED_DEVICE_S / (PR19_RECORDED_BEST * 2.0) * 1e6, 2)

class _ScaleoutHarness:
    """N worker threads on an S-shard broker feeding the single
    resident solver through the REAL SolveCoordinator: the production
    scale-out shape (home-shard dequeue + work stealing, cross-worker
    fusion, one pinned device world) with the scheduler/raft plane
    stripped away, so the measured number is the sharded broker ->
    coordinator -> fused-solve serving path itself."""

    def __init__(self, rs, template_ask, count, n_workers, n_shards,
                 fuse, slo_s, max_batch, max_pending, pipelined=True,
                 lane_spec=None):
        import threading

        from nomad_tpu.scheduler.fleet import SolveCoordinator
        from nomad_tpu.server.blocked_evals import BlockedEvals
        from nomad_tpu.server.eval_broker import EvalBroker
        from nomad_tpu.server.serving import (AdmissionController,
                                              BatchController,
                                              EwmaSolveModel)
        self.rs = rs
        self.template_ask = template_ask
        self.count = count
        self.n_workers = n_workers
        self.max_batch = max_batch
        self.broker = EvalBroker(nack_delay_s=60.0, shards=n_shards)
        self.broker.set_enabled(True)
        self.blocked = BlockedEvals(self.broker)
        self.blocked.set_enabled(True)
        self.model = EwmaSolveModel()
        self.controller = BatchController(self.model, slo_budget_s=slo_s,
                                          max_batch=max_batch)
        self.admission = AdmissionController(
            max_pending=max_pending, protect_priority=80,
            ns_rate=1e9, ns_burst=1e9, brownout_after_s=0.25)
        self.coordinator = None
        #: lane mode (ISSUE 20): each pipelined round dispatches up to
        #: `round_b` member batches as ONE chunked scan-of-vmap call
        #: (`solve_stream_async(..., lanes=L)`), padding ragged rounds
        #: with zero-placement batches so every leg compiles exactly
        #: one (lanes, B) kernel variant.  lane_spec keys:
        #:   lanes      fixed width L (ignored when controller set)
        #:   controller LaneWidthController -> adaptive width per round
        #:   families   N dc-pinned family jobs cycled over lane slots
        #:              (conflict-aware ordering via form_lanes)
        #:   round_b    member batches per lane call (default: lanes)
        self.lane_spec = dict(lane_spec) if lane_spec else None
        if self.lane_spec is not None:
            self.lane_ctrl = self.lane_spec.get("controller")
            self.lane_width = (self.lane_ctrl.width if self.lane_ctrl
                               else max(1, int(self.lane_spec["lanes"])))
            self.lane_round_b = int(
                self.lane_spec.get("round_b", 0)) or max(
                self.lane_width,
                self.lane_ctrl.max_width if self.lane_ctrl else 0)
            self.lane_families = int(self.lane_spec.get("families", 0))
            self._fam_rot = 0
            self._lane_pb = {}       # (slot_kind, n) -> PackedBatch
            self._lane_pad = {}      # slot -> zero-placement pad batch
            self.lane_rounds = 0
            self.lane_calls = 0
            self.lane_bounced = 0
            self.lane_committed = 0
            self.lane_width_hist = []
        #: pipelined legs: the coordinator finish phase owns ack +
        #: latency accounting (the drain leader releases submitters
        #: only after fetch); serialized legs ack in the worker loop
        self._coord_acks = False
        #: pipelined legs use the ISSUE 19 batched broker ops; the
        #: pr17 reference leg keeps PR 17's per-eval pause/ack calls so
        #: the A/B measures the whole serving-path delta
        self.batched_ops = bool(pipelined)
        #: worker back-off bound: stop dequeueing once this many
        #: submissions are queued behind the in-flight round.  Lane
        #: rounds fuse `round_b` member batches, so the backlog must
        #: hold a whole round's worth before dequeueing pauses —
        #: backing off at 1 would starve lane rounds down to one lane
        self._pending_bound = (self.lane_round_b
                               if self.lane_spec is not None else 1)
        if fuse and n_workers > 1:
            if pipelined:
                fused_cap = max_batch * (self.lane_round_b
                                         if self.lane_spec is not None
                                         else 1)
                self.coordinator = SolveCoordinator(
                    None, max_fused=fused_cap,
                    dispatch_fn=(self._dispatch_lane_round
                                 if self.lane_spec is not None
                                 else self._dispatch_round),
                    finish_fn=self._finish_round)
                self._coord_acks = True
            else:
                # PR-17 shape: fused but serialized end to end — the
                # same-machine reference the pipelined legs are
                # measured against
                self.coordinator = SolveCoordinator(
                    None, max_fused=max_batch,
                    solve_fn=lambda _srv, _w, batch: self._solve(
                        [e for e, _t in batch]))
        self.arrival_t = {}
        self.readmitted = set()         # excluded from the percentiles
        self.lat_s = []
        self.completed = 0
        self.offered = 0
        self.device_busy_s = 0.0
        self.device_waves = 0
        self.solve_calls = 0
        #: leader-serial stage totals (ISSUE 19): pack/dispatch/device/
        #: fetch/apply over the measured window.  `fetch` is the wall
        #: blocked on the device result and OVERLAPS `device` (the
        #: union-interval accounting) — the largest-stage comparison
        #: excludes it.
        self.stages = {k: 0.0 for k in
                       ("pack", "dispatch", "device", "fetch", "apply")}
        #: host->device bytes each round's dispatch actually shipped
        #: (ISSUE 20 satellite: the staging-buffer + stream-stack-cache
        #: work should drive steady-state rounds to ~0)
        self.bytes_shipped = 0
        self._prev_fetch_done = 0.0
        #: pipelined-path packed-batch memo by chunk size: the template
        #: asks carry no per-eval state, so every round's chunk packs to
        #: identical tensors — the `pack_batch_cached` steady-state
        #: idiom, which also keeps the dispatch from re-shipping fresh
        #: host arrays to the device each round
        self._pb_cache = {}
        self._solve_lock = threading.Lock()
        self._lat_lock = threading.Lock()
        self.stop = threading.Event()
        self._seq = 0

    def reset_window(self):
        """Drop warmup accounting; the measured window starts now."""
        with self._lat_lock:
            self.lat_s.clear()
            self.completed = 0
        self.device_busy_s = 0.0
        self.device_waves = 0
        self.solve_calls = 0
        self.stages = {k: 0.0 for k in self.stages}
        self.bytes_shipped = 0
        if self.lane_spec is not None:
            self.lane_rounds = 0
            self.lane_calls = 0
            self.lane_bounced = 0
            self.lane_committed = 0
            self.lane_width_hist = []

    def ingress(self, ev):
        self.offered += 1
        self.arrival_t[ev.id] = time.perf_counter()
        if self.admission.offer(ev, self.broker.ready_count()):
            self.broker.enqueue(ev)
            return True
        self.blocked.shed(ev)
        return False

    def ingress_burst(self, evs):
        """Admit a burst with one ready-count probe and one bulk
        enqueue; returns the number admitted."""
        now = time.perf_counter()
        ready = self.broker.ready_count()
        admitted = []
        for ev in evs:
            self.offered += 1
            self.arrival_t[ev.id] = now
            if self.admission.offer(ev, ready):
                admitted.append(ev)
            else:
                self.blocked.shed(ev)
        if admitted:
            self.broker.enqueue_batch(admitted)
        return len(admitted)

    def worker_loop(self, index):
        broker = self.broker
        # batch hold-back bound: wait for a full batch only while the
        # oldest ready eval still has most of its SLO budget left
        hold_age_s = self.controller.slo_budget_s * 0.25
        while not self.stop.is_set():
            if self._coord_acks and self.coordinator is not None \
                    and self.coordinator.pending() >= self._pending_bound:
                # pending bound (fire-and-forget legs): with a whole
                # round already queued behind the in-flight one the
                # device cannot go idle before this worker's next pass,
                # so dequeueing MORE now only fragments the backlog into
                # partial rounds and stretches p99
                self.stop.wait(0.0002)
                continue
            ready = broker.ready_count()
            if self.batched_ops and index >= 2 \
                    and ready < self.max_batch * index:
                # staggered engagement (pipelined legs): workers 0 and 1
                # always run — one leads the drain while the other
                # dequeues and submits the NEXT round, which is the
                # cross-round overlap the pipeline depends on.  Worker
                # k >= 2 wakes only once k full batches are backlogged:
                # extra dequeue threads split one batch N ways, shrinking
                # every fused round and spending GIL slices on dequeue
                # parallelism the single drain leader cannot use.
                self.stop.wait(0.001)
                self._readmit()
                continue
            target = self.controller.target_batch(
                ready, broker.oldest_ready_age())
            if self.batched_ops and ready and ready < self.max_batch \
                    and broker.oldest_ready_age() < hold_age_s:
                # hold-back (pipelined legs): a short wait lets the
                # feeder fill a whole max_batch — fixed-size rounds
                # amortize the per-dispatch kernel cost and keep the
                # packed-batch memo hot, and the age bound keeps the
                # wait invisible to p99
                self.stop.wait(0.0002)
                continue
            batch = broker.dequeue_batch(["service"], target, 0.002,
                                         home=index)
            if not batch:
                self._readmit()
                continue
            t0 = time.perf_counter()
            if self.batched_ops:
                broker.pause_nack_batch(
                    [(ev.id, tok) for ev, tok in batch])
            else:
                for ev, tok in batch:
                    broker.pause_nack_timeout(ev.id, tok)
            if self.coordinator is not None:
                if self._coord_acks:
                    # fire-and-forget fan-back: the round's finish_fn
                    # acks and records latency, so the submitter goes
                    # straight back to dequeueing — a blocked submitter
                    # would leave the device idle for a whole dequeue
                    self.coordinator.submit_nowait(index, batch)
                else:
                    self.coordinator.submit(index, batch)
                    self._finalize(batch, t0)
            else:
                self._solve([e for e, _t in batch])
                self._finalize(batch, t0)
            self._readmit()

    def _finalize(self, batch, t0):
        """Serialized-path completion: batched ack, latency fan-back,
        end-to-end wall into the sizing model (device ~= wall when
        nothing overlaps)."""
        now = time.perf_counter()
        if self.batched_ops:
            self.broker.ack_batch([(ev.id, tok) for ev, tok in batch])
        else:
            for ev, tok in batch:
                self.broker.ack(ev.id, tok)
        lats = []
        for ev, _tok in batch:
            t_arr = self.arrival_t.pop(ev.id, None)
            if t_arr is not None and ev.id not in self.readmitted:
                lats.append(now - t_arr)
        with self._lat_lock:
            self.lat_s.extend(lats)
            self.completed += len(batch)
        self.model.observe(len(batch), now - t0)

    def _readmit(self):
        # drain capacity back to the shed lane — also the hook that
        # clears brownout once the queue is under the low watermark
        quota = self.admission.readmit_quota(
            self.broker.ready_count(), batch=self.max_batch)
        if quota > 0:
            for ev in self.blocked.pop_shed(quota):
                self.readmitted.add(ev.id)
                self.broker.enqueue(ev)

    def _solve(self, evs):
        # one fused device call for however many evals the coordinator
        # coalesced; identical ask signatures merge to one packed row.
        # The coordinator's round can overshoot max_fused by one
        # member's batch, so chunk to the packed capacity — still a
        # single stream dispatch (jobs are unique per stream here)
        with self._solve_lock:
            for lo in range(0, len(evs), self.max_batch):
                n = min(self.max_batch, len(evs) - lo)
                masks, _keys = self.rs.merge_asks(
                    [self.template_ask] * n)
                pb = self.rs.pack_batch(masks)
                self._seq += 1
                # one stream per chunk: every chunk shares the template
                # job identity, and a job may appear in at most one
                # batch per stream
                self.rs.solve_stream([pb], seeds=[self._seq])
                self.device_busy_s += self.rs.last_solve_stats["wall_s"]
                waves = getattr(self.rs, "last_waves", None)
                if waves is not None:
                    import numpy as _np
                    self.device_waves += int(_np.asarray(waves).sum())
                self.solve_calls += 1

    # ----------------------- pipelined round (ISSUE 19) -----------------
    # The coordinator's drain leader calls _dispatch_round for batch b+1
    # BEFORE _finish_round for batch b: the device solves b while the
    # leader packs b+1.  Both run on the single leader thread, so no
    # lock is held across the blocking fetch (the LOCK305 shape).

    def _dispatch_round(self, _server, _worker, batch):
        rnd = _PipeRound(list(batch))
        rnd.t_dispatch_start = time.perf_counter()
        evs = rnd.batch
        for lo in range(0, len(evs), self.max_batch):
            n = min(self.max_batch, len(evs) - lo)
            t0 = time.perf_counter()
            pb = self._pb_cache.get(n)
            if pb is None:
                masks, _keys = self.rs.merge_asks(
                    [self.template_ask] * n)
                pb = self.rs.pack_batch(masks)
                self._pb_cache[n] = pb
            t1 = time.perf_counter()
            self._seq += 1
            rnd.handles.append(
                self.rs.solve_stream_async([pb], seeds=[self._seq]))
            rnd.waves.append(getattr(self.rs, "last_waves", None))
            self.bytes_shipped += getattr(self.rs,
                                          "last_dispatch_bytes", 0) or 0
            t2 = time.perf_counter()
            self.stages["pack"] += t1 - t0
            self.stages["dispatch"] += t2 - t1
        rnd.t_dispatched = time.perf_counter()
        return rnd

    # ----------------------- lane round (ISSUE 20) ----------------------
    # One fused solve call carries up to round_b member batches through
    # the chunked scan-of-vmap: serial depth B -> B/L.  Ragged rounds
    # are padded with zero-placement batches so every leg runs exactly
    # one compiled (lanes, B) kernel variant — a mid-window retrace
    # would eat the whole measured window.

    def _lane_member_pb(self, slot, n):
        """Member batch for lane `slot` holding `n` fused evals.  Each
        slot carries a distinct synthetic job identity (a job may
        appear in at most one batch per stream); the family variant
        additionally pins each slot's job to one datacenter, which is
        the conflict footprint form_lanes separates on."""
        if self.lane_families:
            f = slot % self.lane_families
            key = ("fam", f, n)
            pb = self._lane_pb.get(key)
            if pb is None:
                job = make_job(2, 9000 + f, self.count)
                job.id = f"lane-fam-{f}"
                job.name = job.id
                job.datacenters = [f"dc{f % 4}"]
                masks, _keys = self.rs.merge_asks(
                    [asks_for(job)[0]] * n)
                pb = self.rs.pack_batch(masks, job_keys={("fam", f)})
                self._lane_pb[key] = pb
            return pb, (f"dc{f % 4}",)
        key = ("lane", slot, n)
        pb = self._lane_pb.get(key)
        if pb is None:
            masks, _keys = self.rs.merge_asks([self.template_ask] * n)
            pb = self.rs.pack_batch(masks, job_keys={("lane", slot)})
            self._lane_pb[key] = pb
        # template members share every node as footprint; the former
        # has nothing to separate, so footprint is the slot itself
        return pb, (slot,)

    def _lane_pad_pb(self, i, like):
        """Zero-placement pad batch: same tensors (same compiled
        shape), n_place=0 so the kernel commits nothing for it."""
        pad = self._lane_pad.get(i)
        if pad is None:
            import copy as _copy
            pad = _copy.copy(like)
            pad.n_place = 0
            pad.job_keys = {("pad", i)}
            self._lane_pad[i] = pad
        return pad

    def _dispatch_serial_tail(self, rnd, n_evs):
        """Serial B=1 dispatch for a lane round's ragged remainder:
        any eval count's pow2 `group_count_hint` bucket is already
        compiled by the startup warm loop, so the tail never retraces
        — only FULL max_batch member batches ride the lane call (a
        ragged member would shift the static hint and retrace
        mid-window)."""
        t0 = time.perf_counter()
        pb = self._pb_cache.get(n_evs)
        if pb is None:
            masks, _keys = self.rs.merge_asks(
                [self.template_ask] * n_evs)
            pb = self.rs.pack_batch(masks)
            self._pb_cache[n_evs] = pb
        t1 = time.perf_counter()
        self._seq += 1
        rnd.handles.append(
            self.rs.solve_stream_async([pb], seeds=[self._seq]))
        rnd.waves.append(getattr(self.rs, "last_waves", None))
        self.bytes_shipped += getattr(self.rs,
                                      "last_dispatch_bytes", 0) or 0
        t2 = time.perf_counter()
        self.stages["pack"] += t1 - t0
        self.stages["dispatch"] += t2 - t1

    def _dispatch_lane_round(self, _server, _worker, batch):
        from nomad_tpu.scheduler.fleet import form_lanes
        rnd = _PipeRound(list(batch))
        rnd.t_dispatch_start = time.perf_counter()
        evs = rnd.batch
        lanes = self.lane_width
        n_full = len(evs) // self.max_batch
        if lanes <= 1 or n_full < 2:
            # too few full member batches for a chunk: serial rounds
            # (also the adaptive controller's width-1 regime)
            for lo in range(0, len(evs), self.max_batch):
                self._dispatch_serial_tail(
                    rnd, min(self.max_batch, len(evs) - lo))
            self.lane_rounds += 1
            rnd.t_dispatched = time.perf_counter()
            return rnd
        t0 = time.perf_counter()
        # adaptive legs dispatch B=width calls (every pow2 width's
        # (L, B=L) variant is warmed); fixed legs dispatch B=round_b
        # (the families leg runs round_b=2*width -> a 2-chunk scan)
        call_b = lanes if self.lane_ctrl is not None \
            else self.lane_round_b
        members = []
        for slot in range(n_full):
            pb, footprint = self._lane_member_pb(
                (self._fam_rot + slot) if self.lane_families else slot,
                self.max_batch)
            members.append((pb, footprint))
        if self.lane_families:
            self._fam_rot = (self._fam_rot + len(members)) \
                % self.lane_families
            # conflict-aware chunk formation: order members so each
            # consecutive `lanes`-block holds disjoint dc footprints
            members = form_lanes(members, lanes,
                                 key_fn=lambda m: m[1])
        t1 = time.perf_counter()
        self.stages["pack"] += t1 - t0
        for lo in range(0, len(members), call_b):
            group = [pb for pb, _fp in members[lo:lo + call_b]]
            while len(group) < call_b:
                group.append(self._lane_pad_pb(len(group), group[-1]))
            td = time.perf_counter()
            seeds = []
            for _ in group:
                self._seq += 1
                seeds.append(self._seq)
            rnd.handles.append(self.rs.solve_stream_async(
                group, seeds=seeds, lanes=lanes))
            rnd.waves.append(getattr(self.rs, "last_waves", None))
            raw = getattr(self.rs, "last_lane_counters", None)
            if raw is not None:
                # device scalars captured AT dispatch (the attribute is
                # per-call state; the next dispatch overwrites it) and
                # fetched in the finish phase after the solve completes
                rnd.lane_raw.append(raw)
            self.bytes_shipped += getattr(self.rs,
                                          "last_dispatch_bytes", 0) or 0
            self.stages["dispatch"] += time.perf_counter() - td
            self.lane_calls += 1
        rem = len(evs) - n_full * self.max_batch
        if rem:
            self._dispatch_serial_tail(rnd, rem)
        self.lane_rounds += 1
        rnd.t_dispatched = time.perf_counter()
        return rnd

    def _finish_round(self, _server, _worker, rnd):
        import numpy as _np
        t0 = time.perf_counter()
        for h in rnd.handles:
            self.rs.finish_stream(h)
        now = time.perf_counter()
        self.stages["fetch"] += now - t0
        # device-pipeline busy as the union of in-order intervals
        # [dispatch start, fetch done] — enqueue + h2d + kernel, the
        # same span PR-17's synchronous solve wall covered — with each
        # round's interval clipped to start after the previous round's
        # fetch completed, so overlapped rounds are never double-counted
        device = max(0.0, now - max(rnd.t_dispatch_start,
                                    self._prev_fetch_done))
        self._prev_fetch_done = now
        self.device_busy_s += device
        self.stages["device"] += device
        self.solve_calls += len(rnd.handles)
        for w in rnd.waves:
            if w is not None:
                self.device_waves += int(_np.asarray(w).sum())
        # sizing-model feed: DEVICE time, not round wall — the round
        # wall double-counts the neighbor round's in-flight solve (see
        # ServingTier.note_device_solve)
        self.model.observe(len(rnd.batch), device)
        if self.lane_spec is not None and rnd.lane_raw:
            b = c = 0
            for raw in rnd.lane_raw:
                # per-member device arrays; the sum syncs AFTER the
                # round's fetch, so this is a host add, not a stall
                b += int(_np.asarray(raw["bounced"]).sum())
                c += int(_np.asarray(raw["committed"]).sum())
            self.lane_bounced += b
            self.lane_committed += c
            if self.lane_ctrl is not None:
                rate = b / max(b + c, 1)
                # device_frac: is the device stage still dominant over
                # the leader-serial breakdown?  (fetch overlaps device,
                # excluded — same rule as largest_stage)
                host = sum(v for k, v in self.stages.items()
                           if k not in ("device", "fetch"))
                frac = self.stages["device"] \
                    / max(self.stages["device"] + host, 1e-9)
                w = self.lane_ctrl.record(rate, frac)
                if w != self.lane_width:
                    self.lane_width = w
                self.lane_width_hist.append(w)
        t1 = time.perf_counter()
        self.broker.ack_batch([(ev.id, tok) for ev, tok in rnd.batch])
        lats = []
        for ev, _tok in rnd.batch:
            t_arr = self.arrival_t.pop(ev.id, None)
            if t_arr is not None and ev.id not in self.readmitted:
                lats.append(now - t_arr)
        with self._lat_lock:
            self.lat_s.extend(lats)
            self.completed += len(rnd.batch)
        self.stages["apply"] += time.perf_counter() - t1


class _PipeRound:
    """One dispatched-not-fetched fused round in the bench harness."""
    __slots__ = ("batch", "handles", "waves", "lane_raw",
                 "t_dispatch_start", "t_dispatched")

    def __init__(self, batch):
        self.batch = batch       # [(Evaluation, token)]
        self.handles = []        # device-side packed results
        self.waves = []          # per-chunk device wave counters
        self.lane_raw = []       # per-call lane counters (device
        #                          scalars; fetched in finish)
        self.t_dispatch_start = 0.0
        self.t_dispatched = 0.0


def _run_scaleout_leg(rs, template_ask, count, n_workers, n_shards,
                      fuse, duration_s, slo_s, max_batch, max_pending,
                      used0, warmup_s=0.4, pipelined=True,
                      lane_spec=None):
    """Saturate one (workers, shards, fuse) config and return its
    record: the feeder offers as fast as admission allows, so the
    completed rate IS the config's capacity."""
    import gc
    import threading

    from nomad_tpu.structs import Evaluation
    from nomad_tpu.utils.metrics import global_metrics as _gm

    gc.collect()
    # collector off for the measured window (re-enabled after the
    # join): a mid-window gen2 pass stops every thread for tens of ms,
    # which lands on every queued eval's latency at once — the classic
    # phantom p99 spike.  The harness allocates no cycles, so garbage
    # cannot accumulate meaningfully in a few seconds.  Applies to
    # every leg equally.
    gc.disable()
    rs.reset_usage(used0=used0)
    # GIL hygiene for the measured window: the default 5ms switch
    # interval lets the CPU-bound feeder hog whole 5ms slices while the
    # drain leader's dispatch waits; a finer interval is the standard
    # setting for latency-sensitive mixed IO/CPU thread pools.  Applies
    # to every leg equally.
    old_switch = sys.getswitchinterval()
    sys.setswitchinterval(0.0005)
    h = _ScaleoutHarness(rs, template_ask, count, n_workers, n_shards,
                         fuse, slo_s, max_batch, max_pending,
                         pipelined=pipelined, lane_spec=lane_spec)
    c0 = _gm.dump()["counters"]
    workers = [threading.Thread(target=h.worker_loop, args=(i,),
                                daemon=True) for i in range(n_workers)]
    for t in workers:
        t.start()
    t_start = time.perf_counter()
    t_meas = t_start
    i = 0
    warmup_done = False
    while time.perf_counter() - t_start < warmup_s + duration_s:
        if not warmup_done and time.perf_counter() - t_start >= warmup_s:
            # restart the clocks: the EWMA model is trained, drop the
            # warmup completions/latencies from the measured window
            h.reset_window()
            t_meas = time.perf_counter()
            warmup_done = True
        # burst ingress: one admission probe + one bulk enqueue per
        # burst keeps the feeder's GIL share small at saturation (the
        # per-eval enqueue's lock + condition traffic was the single
        # largest host cost at 20k evals/s).  Explicit sequential ids
        # skip the uuid default_factory — the single largest cost of
        # constructing a synthetic eval, and harness cost, not serving
        # cost (real ingress arrives with ids)
        burst = [Evaluation(id=f"sc-{i + j}", job_id=f"sc-{i + j}",
                            priority=50)
                 for j in range(32)]
        i += 32
        if h.ingress_burst(burst) == 0:
            time.sleep(0.0005)       # admission-bounded: back off
    elapsed = time.perf_counter() - t_meas
    h.stop.set()
    for t in workers:
        t.join(timeout=5.0)
    sys.setswitchinterval(old_switch)
    gc.enable()
    c1 = _gm.dump()["counters"]
    lat = latency_summary(h.lat_s)
    stages = {k: round(v, 3) for k, v in h.stages.items()}
    # largest stage over the leader-serial breakdown; `fetch` is the
    # blocked-on-device wall and overlaps `device`, so it is excluded
    # from the comparison (it is an alias of device wait, not work)
    comparable = {k: v for k, v in h.stages.items() if k != "fetch"}
    largest = (max(comparable, key=comparable.get)
               if any(comparable.values()) else None)
    rec = {
        "workers": n_workers, "shards": n_shards, "fused": bool(fuse),
        "pipelined": bool(pipelined and fuse and n_workers > 1),
        "completed": h.completed,
        "evals_per_sec": round(h.completed / max(elapsed, 1e-9), 1),
        "p50_ms": lat["p50_ms"], "p99_ms": lat["p99_ms"],
        "device_occupancy": round(h.device_busy_s
                                  / max(elapsed, 1e-9), 3),
        "device_waves": h.device_waves,
        "solve_calls": h.solve_calls,
        "evals_per_solve": round(h.completed
                                 / max(h.solve_calls, 1), 1),
        "cross_worker_rounds": round(
            c1.get("coordinator.cross_worker_rounds", 0)
            - c0.get("coordinator.cross_worker_rounds", 0)),
        "stages_s": stages,
        "largest_stage": largest,
        "bytes_shipped": h.bytes_shipped,
    }
    if lane_spec is not None:
        b, c = h.lane_bounced, h.lane_committed
        rec["lanes"] = ("auto" if h.lane_ctrl is not None
                        else h.lane_width)
        rec["lane_rounds"] = h.lane_rounds
        rec["lane_calls"] = h.lane_calls
        rec["revalidation"] = {
            "bounced": b, "committed": c,
            "bounce_rate": round(b / max(b + c, 1), 4),
        }
        if h.lane_families:
            rec["lane_families"] = h.lane_families
        if h.lane_ctrl is not None:
            hist = h.lane_width_hist
            rec["lane_width_final"] = h.lane_width
            # compressed trajectory: width after each round, run-length
            # encoded so a 2s window's hundreds of rounds stay readable
            traj = []
            for w in hist:
                if traj and traj[-1][0] == w:
                    traj[-1][1] += 1
                else:
                    traj.append([w, 1])
            rec["lane_width_trajectory"] = traj
    return rec


def _run_group_commit_leg(group_commit, n_plans=300, n_nodes=64):
    """Plan applies through the real PlanApplier against a durable
    fsynced log: group_commit=K amortizes one fsync (and one raft
    entry) over K plans."""
    import tempfile
    import threading

    from nomad_tpu import mock
    from nomad_tpu.server.plan_apply import PlanApplier
    from nomad_tpu.server.plan_queue import PlanQueue
    from nomad_tpu.state.store import StateStore
    from nomad_tpu.structs import Plan
    from nomad_tpu.utils.codec import to_wire

    store = StateStore()
    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.node_resources.cpu = 1 << 20
        node.node_resources.memory_mb = 1 << 20
        node.reserved_resources.cpu = 0
        node.reserved_resources.memory_mb = 0
        store.upsert_node(i + 1, node)
        nodes.append(node)

    state = {"index": 100, "fsyncs": 0, "entries": 0}
    lock = threading.Lock()
    fh = tempfile.TemporaryFile(mode="w+")

    def _commit(items):
        # leader append: serialize + flush + fsync ONCE per entry, the
        # raft-boltdb discipline the group commit amortizes
        with lock:
            state["index"] += 1
            ix = state["index"]
            fh.write(json.dumps([to_wire(res) for _pl, res in items])
                     + "\n")
            fh.flush()
            os.fsync(fh.fileno())
            state["fsyncs"] += 1
            state["entries"] += 1
        for plan, result in items:
            store.upsert_plan_results(ix, result, job=plan.job)

        def finish(timeout=10.0):
            return ix
        return 0, finish

    queue = PlanQueue()
    queue.set_enabled(True)
    applier = PlanApplier(
        queue, store, None, None,
        apply_async_fn=lambda plan, res: _commit([(plan, res)]),
        apply_batch_async_fn=_commit if group_commit > 1 else None,
        group_commit=group_commit)

    def plan_for(i):
        job = mock.job()
        node = nodes[i % n_nodes]
        plan = Plan(job=job)
        a = mock.alloc(job=job, node_id=node.id)
        for tr in a.allocated_resources.tasks.values():
            tr.networks = []
            tr.cpu = 10
            tr.memory_mb = 10
        plan.node_allocation[node.id] = [a]
        return plan

    plans = [plan_for(i) for i in range(n_plans)]
    applier.start()
    try:
        t0 = time.perf_counter()
        pendings = [queue.enqueue(p) for p in plans]
        for p in pendings:
            result, err = p.future.wait(30.0)
            assert err is None, err
        elapsed = time.perf_counter() - t0
    finally:
        applier.stop()
        queue.set_enabled(False)
        fh.close()
    return {
        "group_commit": group_commit, "plans": n_plans,
        "raft_entries": state["entries"], "fsyncs": state["fsyncs"],
        "plans_per_fsync": round(n_plans / max(state["fsyncs"], 1), 2),
        "plans_per_sec": round(n_plans / max(elapsed, 1e-9), 1),
    }


def run_scaleout(n_nodes=2048, count=4, max_batch=128, slo_ms=50.0,
                 duration_s=2.0, resident=5000, seed=11,
                 grid=((1, 1), (2, 2), (4, 4), (8, 8)),
                 write_detail=True):
    """Scale-out control-plane phase (ISSUE 17 acceptance).

    Sweeps (workers x broker shards) over the sharded-broker ->
    SolveCoordinator -> fused-resident-solve path and reports each
    config's saturated evals/sec at its p99, the device-occupancy
    fraction (fused solve wall over elapsed), and the coordinator's
    cross-worker fusion counters; plus the group-commit leg's
    plans-per-fsync amortization.  The acceptance figure is the best
    config's throughput relative to the single-worker single-shard
    baseline (same solver, same machine — CPU-backend numbers are the
    recorded profile the issue allows; the serialization the
    coordinator removes exists on every backend)."""
    from nomad_tpu.solver.resident import ResidentSolver
    from nomad_tpu.solver.tensorize import Tensorizer

    slo_s = slo_ms / 1000.0
    nodes = make_nodes(n_nodes)
    probe_job = make_job(2, 0, count)
    template_ask = asks_for(probe_job)[0]
    gp_need = len({Tensorizer.ask_signature(a)
                   for a in asks_for(probe_job)})
    t0 = time.perf_counter()
    rs = ResidentSolver(nodes, asks_for(probe_job),
                        gp=1 << max(0, (gp_need - 1).bit_length()),
                        kp=1 << max(0, (count * max_batch - 1)
                                    .bit_length()),
                        max_waves=18)
    used0 = resident_used0(rs.template, n_nodes, resident)
    rs.reset_usage(used0=used0)
    import dataclasses
    k = 1
    while k <= max_batch:
        asks = [dataclasses.replace(template_ask, count=count)] * k
        masks, _keys = rs.merge_asks(asks)
        rs.solve_stream([rs.pack_batch(masks)], seeds=[1])
        k <<= 1
    # lane-variant warmup (ISSUE 20): lanes and B are trace shapes, so
    # each (lanes, B) pair the sweep dispatches compiles exactly once,
    # here — a mid-window retrace would eat the whole measured window.
    # (4, 8) is the families leg's 2-chunk scan; family batches share
    # the template's tensor shapes, so the template warms them too.
    for lane_l, lane_b in ((2, 2), (4, 4), (8, 8), (4, 8)):
        pbs = []
        for s in range(lane_b):
            masks, _keys = rs.merge_asks(
                [dataclasses.replace(template_ask, count=count)]
                * max_batch)
            pbs.append(rs.pack_batch(masks, job_keys={("lane", s)}))
        rs.finish_stream(rs.solve_stream_async(
            pbs, seeds=list(range(1, lane_b + 1)), lanes=lane_l))
    rs.reset_usage(used0=used0)
    startup_s = time.perf_counter() - t0

    # admission bound sized to 2 fused batches of backlog: deep enough
    # that every worker's dequeue fills a whole max_batch (fixed-size
    # rounds keep the packed-batch memo hot and the device waves full),
    # shallow enough that the admitted traffic's p99 stays queue-bounded
    # — with a round queued at the coordinator and one in flight, total
    # in-system work is ~4 rounds, which at the measured service rate
    # keeps p99 inside the 50ms SLO budget
    max_pending = max_batch * 2
    # deterministic trace sampling at a serving-rate-appropriate rate
    # (ISSUE 15's mechanism: per-trace-id crc32 threshold — sampled
    # evals keep whole timelines).  Full-rate tracing costs ~19us per
    # span on this path, which at >10k evals/s is the GIL's whole
    # budget; EVERY leg (baseline, pr17 reference, pipelined sweep)
    # runs under the same rate, so the A/B ratios are unaffected.
    trace_sample = 0.01
    out = {"phase": "scaleout", "n_nodes": n_nodes, "count": count,
           "slo_ms": slo_ms, "max_batch": max_batch,
           "duration_s": duration_s, "max_pending": max_pending,
           "trace_sample": trace_sample,
           "startup_s": round(startup_s, 2), "sweep": []}

    from nomad_tpu.utils.tracing import global_tracer as _gt
    old_sample, old_cut = _gt.sample, _gt._sample_cut
    _gt.sample = trace_sample
    _gt._sample_cut = int(trace_sample * (1 << 32))
    try:
        base = _run_scaleout_leg(rs, template_ask, count, 1, 1, False,
                                 duration_s, slo_s, max_batch,
                                 max_pending, used0)
        out["baseline"] = base
        sys.stderr.write(f"scaleout baseline 1wx1s: "
                         f"{base['evals_per_sec']}/s "
                         f"p99={base['p99_ms']}ms "
                         f"occ={base['device_occupancy']}\n")
        # PR-17 same-machine reference: fused but serialized end to end
        # (the pre-pipeline coordinator) at its best recorded config —
        # the A/B the pipelined sweep's 3x acceptance is measured
        # against, immune to machine-speed drift in the recorded
        # profile
        pr17 = _run_scaleout_leg(rs, template_ask, count, 4, 4, True,
                                 duration_s, slo_s, max_batch,
                                 max_pending, used0, pipelined=False)
        out["pr17_reference"] = pr17
        sys.stderr.write(f"scaleout pr17-ref 4wx4s serialized: "
                         f"{pr17['evals_per_sec']}/s "
                         f"p99={pr17['p99_ms']}ms "
                         f"occ={pr17['device_occupancy']}\n")
        for n_workers, n_shards in grid:
            if (n_workers, n_shards) == (1, 1):
                continue
            rec = _run_scaleout_leg(rs, template_ask, count, n_workers,
                                    n_shards, True, duration_s, slo_s,
                                    max_batch, max_pending, used0)
            out["sweep"].append(rec)
            sys.stderr.write(
                f"scaleout {n_workers}wx{n_shards}s pipelined: "
                f"{rec['evals_per_sec']}/s p99={rec['p99_ms']}ms "
                f"occ={rec['device_occupancy']} "
                f"largest={rec['largest_stage']} "
                f"xw_rounds={rec['cross_worker_rounds']}\n")

        # ---- lane sweep (ISSUE 20): chunked scan-of-vmap rounds ----
        # All lane legs run 2 workers x 2 shards (the recorded PR-19
        # best config); the L=1 serial reference IS that config's plain
        # pipelined leg from the sweep above.  Lane legs fuse L member
        # batches per round, so the admission bound scales with L to
        # keep a full round of backlog behind the in-flight one.
        from nomad_tpu.scheduler.fleet import LaneWidthController
        lane_ref = next((r for r in out["sweep"]
                         if r["workers"] == 2 and r["shards"] == 2),
                        None)
        out["lane_serial_reference"] = lane_ref
        out["lane_sweep"] = []

        def _lane_leg(spec, label, round_b):
            rec = _run_scaleout_leg(
                rs, template_ask, count, 2, 2, True, duration_s,
                slo_s, max_batch, max_batch * round_b * 2, used0,
                lane_spec=spec)
            rec["leg"] = label
            out["lane_sweep"].append(rec)
            rv = rec.get("revalidation", {})
            sys.stderr.write(
                f"scaleout lane {label}: {rec['evals_per_sec']}/s "
                f"p99={rec['p99_ms']}ms "
                f"device={rec['stages_s'].get('device')}s "
                f"bounce={rv.get('bounce_rate')} "
                f"bytes={rec['bytes_shipped']}\n")
            return rec

        for lane_l in (2, 4, 8):
            _lane_leg({"lanes": lane_l}, f"L={lane_l}", lane_l)
        # dc-pinned families: 8 jobs pinned round-robin over 4 dcs,
        # form_lanes packs each 4-lane chunk from disjoint dcs (the
        # conflict-aware formation the coordinator hook exists for)
        _lane_leg({"lanes": 4, "families": 8, "round_b": 8},
                  "L=4 families=8", 8)
        # adaptive width, run LAST: every pow2 (L, B=L) variant is
        # already compiled, so the controller can roam freely
        _lane_leg({"controller": LaneWidthController(max_width=8,
                                                     start=2)},
                  "L=auto", 8)
    finally:
        _gt.sample, _gt._sample_cut = old_sample, old_cut

    # workers sweep must be monotone non-decreasing through 8 (ISSUE 19
    # satellite; 5% jitter tolerance) — a regressing step auto-caps the
    # recommended worker count at the last non-regressing config and
    # records why
    monotone = True
    auto_cap = None
    prev = None
    for rec in out["sweep"]:
        if prev is not None and \
                rec["evals_per_sec"] < prev["evals_per_sec"] * 0.95:
            monotone = False
            # name the culprit stage (ISSUE 20 satellite): the stage
            # whose leader-serial wall grew most vs the previous
            # config — `fetch` overlaps `device` and is excluded, same
            # rule as largest_stage.  At 8x8 the historical culprit is
            # `dispatch`+`pack` (GIL contention: more dequeue threads
            # splitting the same single drain leader's slices), not
            # the device — which is why the auto-cap, not a solver
            # change, is the right fix.
            ps = prev.get("stages_s", {})
            cs = rec.get("stages_s", {})
            deltas = {k: round(cs.get(k, 0.0) - ps.get(k, 0.0), 3)
                      for k in cs if k != "fetch"}
            culprit = (max(deltas, key=deltas.get)
                       if deltas else None)
            auto_cap = {
                "workers": prev["workers"], "shards": prev["shards"],
                "culprit_stage": culprit,
                "stage_deltas_s": deltas,
                "reason": (f"{rec['workers']}x{rec['shards']} regressed "
                           f"to {rec['evals_per_sec']}/s from "
                           f"{prev['evals_per_sec']}/s at "
                           f"{prev['workers']}x{prev['shards']}"
                           + (f"; culprit stage: {culprit} "
                              f"(+{deltas[culprit]}s)"
                              if culprit else "")),
            }
            break
        prev = rec
    out["workers_monotone"] = monotone
    out["workers_auto_cap"] = auto_cap

    # best selection subject to the SLO bound (ISSUE 19 satellite): the
    # raw-throughput winner is recorded, but `best` must hold p99
    # inside the latency budget — a config that wins evals/s by letting
    # the queue blow the SLO is not the config to run
    candidates = [base] + out["sweep"] + out["lane_sweep"]
    best_raw = max(candidates, key=lambda r: r["evals_per_sec"])
    slo_ok = [r for r in candidates if r["p99_ms"] is not None
              and r["p99_ms"] <= slo_ms]
    best = (max(slo_ok, key=lambda r: r["evals_per_sec"])
            if slo_ok else best_raw)
    out["best_raw"] = best_raw
    out["best_meets_slo"] = bool(slo_ok)

    gc_legs = [_run_group_commit_leg(k) for k in (1, 8, 32)]
    out["group_commit"] = gc_legs
    for leg in gc_legs:
        sys.stderr.write(
            f"group-commit K={leg['group_commit']}: "
            f"{leg['plans_per_sec']}/s "
            f"{leg['plans_per_fsync']} plans/fsync\n")

    rel = (best["evals_per_sec"] / base["evals_per_sec"]
           if base["evals_per_sec"] else float("inf"))
    rel_pr17 = (best["evals_per_sec"] / pr17["evals_per_sec"]
                if pr17["evals_per_sec"] else float("inf"))
    amortized = max(leg["plans_per_fsync"] for leg in gc_legs)
    out["best"] = best
    out["relative_speedup"] = round(rel, 2)
    out["relative_speedup_vs_pr17"] = round(rel_pr17, 2)
    out["pr17_recorded_best_evals_per_sec"] = PR17_RECORDED_BEST
    out["acceptance"] = {
        "best_evals_per_sec": best["evals_per_sec"],
        "ge_50k_evals_per_sec": best["evals_per_sec"] >= 50_000,
        "ge_10x_relative": rel >= 10.0,
        "ge_3x_pr17_recorded":
            best["evals_per_sec"] >= 3.0 * PR17_RECORDED_BEST,
        "ge_3x_pr17_same_machine": rel_pr17 >= 3.0,
        "best_meets_slo": bool(slo_ok),
        "bounded_p99_ms": best["p99_ms"],
        "device_occupancy_ge_0_85":
            best["device_occupancy"] >= 0.85,
        "workers_monotone_through_8": bool(monotone or auto_cap),
        "device_largest_stage":
            best.get("largest_stage") == "device",
        "group_commit_amortizes_fsync": amortized > 1.5,
        "backend": "cpu (recorded profile; the issue's 10x target "
                   "binds on accelerator backends)",
    }
    # ---- ISSUE 20 lane acceptance: best lane leg inside the SLO ----
    lane_slo = [r for r in out["lane_sweep"]
                if r["p99_ms"] is not None and r["p99_ms"] <= slo_ms]
    lane_best = (max(lane_slo, key=lambda r: r["evals_per_sec"])
                 if lane_slo
                 else max(out["lane_sweep"],
                          key=lambda r: r["evals_per_sec"]))
    out["lane_best"] = lane_best
    lane_dev_us = (lane_best["stages_s"].get("device", 0.0)
                   / max(lane_best["completed"], 1) * 1e6)
    out["acceptance"]["lane_best_evals_per_sec"] = \
        lane_best["evals_per_sec"]
    out["acceptance"]["lane_ge_40k_evals_per_sec"] = \
        bool(lane_slo) and lane_best["evals_per_sec"] >= 40_000
    out["acceptance"]["lane_ge_50k_stretch"] = \
        bool(lane_slo) and lane_best["evals_per_sec"] >= 50_000
    out["acceptance"]["lane_p99_ms"] = lane_best["p99_ms"]
    out["acceptance"]["lane_bounce_rate"] = \
        lane_best.get("revalidation", {}).get("bounce_rate")
    out["acceptance"]["pr19_recorded_device_us_per_eval"] = \
        PR19_RECORDED_DEVICE_US_PER_EVAL
    out["acceptance"]["lane_device_us_per_eval"] = \
        round(lane_dev_us, 2)
    out["acceptance"]["device_stage_reduced_30pct"] = \
        lane_dev_us <= 0.7 * PR19_RECORDED_DEVICE_US_PER_EVAL
    out["acceptance"]["lane_backend_note"] = (
        "cpu recorded profile: vmapped lanes serialize on a "
        "single-core host, so the 40k and -30% device targets bind on "
        "accelerator backends where lanes are data-parallel; the "
        "conflict-aware formation result (families leg bounce rate vs "
        "unformed L=4) is backend-independent")
    out["ok"] = bool(rel > 1.0
                     and out["acceptance"]["group_commit_amortizes_fsync"])
    if write_detail:
        # merge into BENCH_DETAIL.json preserving the other phases
        path = os.path.join(REPO, "BENCH_DETAIL.json")
        try:
            with open(path) as f:
                detail = json.load(f)
        except (OSError, json.JSONDecodeError):
            detail = {}
        detail["scaleout"] = out
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
    return out


def run_tracing_overhead(n_nodes=10_000, count=64, resident=100_000,
                         batch=32, iters=24, reps=5, warmup=4,
                         write_detail=True):
    """Tracing-overhead leg (ISSUE 10 acceptance): traced vs untraced
    steady-state solve wall at config-3 scale (10K nodes, 100K resident
    allocs, count-64 asks).

    Each iteration solves one fused batch through the resident stream
    engine; the traced leg records per eval exactly what the serving
    path records (create/admit/enqueue/dequeue/batch events plus a
    solve span carrying the ResidentSolver wave/delta counters), so
    the measured delta IS the flight recorder's serving-path cost.
    Legs interleave per rep so transport/CPU drift cancels; the
    acceptance bar is traced within 2% of untraced."""
    import dataclasses

    from nomad_tpu.solver.resident import ResidentSolver
    from nomad_tpu.solver.tensorize import Tensorizer
    from nomad_tpu.utils.tracing import FlightRecorder

    nodes = make_nodes(n_nodes)
    probe_job = make_job(3, 0, count)
    template_ask = asks_for(probe_job)[0]
    gp_need = len({Tensorizer.ask_signature(a)
                   for a in asks_for(probe_job)})
    t0 = time.perf_counter()
    rs = ResidentSolver(nodes, asks_for(probe_job),
                        gp=1 << max(0, (gp_need - 1).bit_length()),
                        kp=1 << max(0, (count * batch - 1)
                                    .bit_length()),
                        max_waves=18)
    used0 = resident_used0(rs.template, n_nodes, resident)
    rs.reset_usage(used0=used0)
    asks = [dataclasses.replace(template_ask, count=count)] * batch
    masks, _keys = rs.merge_asks(asks)
    pb = rs.pack_batch(masks)
    rs.solve_stream([pb], seeds=[1])        # compile outside the legs
    startup_s = time.perf_counter() - t0

    seq = [0]

    def one_iter(rec, i):
        evs = [f"to-{i}-{k}" for k in range(batch)]
        for eid in evs:
            rec.event(eid, "create", parent="", job_id="bench",
                      namespace="default", priority=50)
            rec.event(eid, "admit", admitted=True)
            rec.event(eid, "broker.enqueue", queue="service")
        for eid in evs:
            rec.event(eid, "broker.dequeue", queue_age_s=0.0,
                      delivery=1)
            rec.event(eid, "worker.batch", batch_size=batch,
                      lane="bulk")
        spans = [rec.stage(eid, "solve", job_id="bench", fused=True,
                           fused_batch=batch) for eid in evs]
        seq[0] += 1
        rs.solve_stream([pb], seeds=[seq[0]])
        attrs = rs.trace_attrs()
        for sp in spans:
            sp.set(**attrs)
            sp.end()

    def leg(rec):
        rs.reset_usage(used0=used0)
        for i in range(warmup):
            one_iter(rec, i)
        t = time.perf_counter()
        for i in range(iters):
            one_iter(rec, warmup + i)
        return time.perf_counter() - t

    off_rec = FlightRecorder(depth=512, enabled=False)
    on_rec = FlightRecorder(depth=512, enabled=True)
    walls_off, walls_on = [], []
    for _rep in range(reps):
        walls_off.append(leg(off_rec))
        walls_on.append(leg(on_rec))
    # best-of-reps: the solve wall on a shared CPU carries multi-% rep-
    # to-rep noise that dwarfs the recorder's microsecond-scale appends;
    # the per-leg FLOOR isolates the systematic cost the acceptance bar
    # is about (both legs get identical treatment)
    off = min(walls_off)
    on = min(walls_on)
    overhead_pct = 100.0 * (on - off) / max(off, 1e-9)
    out = {
        "phase": "tracing_overhead",
        "n_nodes": n_nodes, "count": count, "resident": resident,
        "batch": batch, "iters": iters, "reps": reps,
        "startup_s": round(startup_s, 2),
        "untraced_wall_s": [round(w, 4) for w in walls_off],
        "traced_wall_s": [round(w, 4) for w in walls_on],
        "untraced_evals_per_sec": round(batch * iters / off, 1),
        "traced_evals_per_sec": round(batch * iters / on, 1),
        "overhead_pct": round(overhead_pct, 3),
        "recorder": on_rec.stats(),
        "acceptance": {"traced_within_2pct": overhead_pct <= 2.0},
    }
    out["ok"] = bool(out["acceptance"]["traced_within_2pct"])
    if write_detail:
        # merge into BENCH_DETAIL.json preserving the other phases
        path = os.path.join(REPO, "BENCH_DETAIL.json")
        try:
            with open(path) as f:
                detail = json.load(f)
        except (OSError, json.JSONDecodeError):
            detail = {}
        detail["tracing_overhead"] = out
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
    return out


def run_telemetry_overhead(n_nodes=10_000, count=64, resident=100_000,
                           batch=32, iters=24, reps=9, warmup=4,
                           sample_every=5, churn_steps=8,
                           write_detail=True):
    """Telemetry leg (ISSUE 15 acceptance): steady-state solve wall
    with the fleet health kernel sampling every `sample_every` solves
    vs never, at config-3 scale (10K nodes, 100K resident allocs,
    count-64 asks).

    The sampled leg is deliberately harsher than production: at this
    scale the stream runs ~10 solves/s, so sample_every=5 is ~2 Hz —
    roughly 10x the server's shipped duty cycle (one sample per
    HEALTH_SAMPLE_EVERY=5 export beats, i.e. per 5 s).  The record
    also carries the measured per-sample unit cost
    (health_sample_cost_ms, ~2 ms at this scale: on the CPU backend
    the kernel serializes with solves on one XLA stream, so the unit
    cost IS the kernel wall) so any cadence's overhead can be read
    off directly.  Legs interleave per rep so transport/CPU drift
    cancels; min-of-reps isolates the systematic cost from
    shared-CPU noise (same floor treatment as the tracing leg
    above).

    A second churn phase strands CPU on a growing fraction of nodes
    (plenty of memory/disk free, but less CPU than the smallest probe
    ask needs) and records the fragmentation-index trajectory the
    health plane reports, through a real TimeSeriesStore ring so the
    record also proves the series plumbing end to end."""
    import dataclasses

    import numpy as np

    from nomad_tpu.solver.resident import ResidentSolver
    from nomad_tpu.solver.tensorize import Tensorizer
    from nomad_tpu.telemetry.health import (device_health_counters,
                                            device_health_raw,
                                            fetch_health)
    from nomad_tpu.telemetry.series import TimeSeriesStore

    nodes = make_nodes(n_nodes)
    probe_job = make_job(3, 0, count)
    template_ask = asks_for(probe_job)[0]
    gp_need = len({Tensorizer.ask_signature(a)
                   for a in asks_for(probe_job)})
    t0 = time.perf_counter()
    rs = ResidentSolver(nodes, asks_for(probe_job),
                        gp=1 << max(0, (gp_need - 1).bit_length()),
                        kp=1 << max(0, (count * batch - 1)
                                    .bit_length()),
                        max_waves=18)
    used0 = resident_used0(rs.template, n_nodes, resident)
    rs.reset_usage(used0=used0)
    asks = [dataclasses.replace(template_ask, count=count)] * batch
    masks, _keys = rs.merge_asks(asks)
    pb = rs.pack_batch(masks)
    rs.solve_stream([pb], seeds=[1])        # compile outside the legs
    device_health_counters(rs)              # compile the health kernel
    startup_s = time.perf_counter() - t0

    seq = [0]

    def leg(sample_health):
        rs.reset_usage(used0=used0)
        it = [0]
        # double-buffered sampling, the way a production device-side
        # sampler runs: dispatch this beat's kernel, materialize the
        # PREVIOUS beat's (long since done) — a blocking fetch right
        # after dispatch would charge the stream's in-flight tail to
        # the sample
        pending = [None]

        def fetch_pending():
            if pending[0] is not None:
                fetch_health(pending[0])
                pending[0] = None

        def one_iter():
            seq[0] += 1
            it[0] += 1
            rs.solve_stream([pb], seeds=[seq[0]])
            if sample_health and it[0] % sample_every == 0:
                fetch_pending()
                pending[0] = device_health_raw(rs)

        for _ in range(warmup):
            one_iter()
        t = time.perf_counter()
        for _ in range(iters):
            one_iter()
        fetch_pending()
        return time.perf_counter() - t

    walls_off, walls_on = [], []
    for _rep in range(reps):
        walls_off.append(leg(False))
        walls_on.append(leg(True))
    off = min(walls_off)
    on = min(walls_on)
    overhead_pct = 100.0 * (on - off) / max(off, 1e-9)

    # ---- churn phase: stranded-CPU fragmentation trajectory --------
    # The smallest config-3 group asks 400 CPU; leaving 350 free makes
    # a node un-placeable while its memory/disk headroom stays large —
    # the classic fragmentation picture the index is built to surface.
    avail = np.asarray(rs.template.avail, np.float32)
    # start at t=1: the points() cursor is bucket_start > since and the
    # default since is 0, which would hide a bucket starting at 0
    fake_t = [1.0]
    churn_store = TimeSeriesStore(resolutions=((1, 4 * churn_steps),),
                                  clock=lambda: fake_t[0])
    traj = []
    for step in range(churn_steps + 1):
        frac = step / churn_steps
        n_churn = int(frac * n_nodes)
        churned = used0.copy()
        if n_churn:
            churned[:n_churn, 0] = np.maximum(
                avail[:n_churn, 0] - 350.0, churned[:n_churn, 0])
        rs.reset_usage(used0=churned)
        hc = device_health_counters(rs)
        frag = hc.fragmentation_index()
        traj.append({"churn_frac": round(frac, 3),
                     "fragmentation_index": round(frag, 4),
                     "nodes_stranded": hc.nodes_stranded,
                     "nodes_busy": hc.nodes_busy})
        churn_store.record("health.fragmentation_index", frag,
                           now=fake_t[0])
        fake_t[0] += 1.0
    churn_store.flush(now=fake_t[0])
    ring = churn_store.points("health.fragmentation_index", res=1)
    frags = [p["fragmentation_index"] for p in traj]
    # samples landing inside the timed window (iteration counter spans
    # warmup too, so the modulo grid does not restart at the timer)
    n_samples = len([i for i in range(warmup + 1, warmup + iters + 1)
                     if i % sample_every == 0])
    out = {
        "phase": "telemetry",
        "n_nodes": n_nodes, "count": count, "resident": resident,
        "batch": batch, "iters": iters, "reps": reps,
        "sample_every": sample_every,
        "startup_s": round(startup_s, 2),
        "unsampled_wall_s": [round(w, 4) for w in walls_off],
        "sampled_wall_s": [round(w, 4) for w in walls_on],
        "unsampled_evals_per_sec": round(batch * iters / off, 1),
        "sampled_evals_per_sec": round(batch * iters / on, 1),
        "overhead_pct": round(overhead_pct, 3),
        "health_samples_per_leg": n_samples,
        "health_sample_cost_ms": round(
            1000.0 * (on - off) / max(n_samples, 1), 3),
        "fragmentation_trajectory": traj,
        "series_ring_points": len(ring),
        "acceptance": {
            "telemetry_within_2pct": overhead_pct <= 2.0,
            "fragmentation_monotone": all(
                b >= a - 1e-9 for a, b in zip(frags, frags[1:])),
            "fragmentation_rises": frags[-1] > frags[0] + 0.25,
            "ring_kept_every_sample": len(ring) == churn_steps + 1,
        },
    }
    out["ok"] = all(out["acceptance"].values())
    if write_detail:
        # merge into BENCH_DETAIL.json preserving the other phases
        path = os.path.join(REPO, "BENCH_DETAIL.json")
        try:
            with open(path) as f:
                detail = json.load(f)
        except (OSError, json.JSONDecodeError):
            detail = {}
        detail["telemetry"] = out
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
    return out


def measure_transport_rtt():
    """Median fixed round-trip of a trivial device call + result fetch:
    the per-call floor this transport imposes regardless of work."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    f = jax.jit(lambda a: a + 1)
    x = jax.device_put(jnp.zeros(16))
    np.asarray(f(x))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(f(x))
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def run_ours_latency(config, n_nodes, n_evals, count, resident):
    """Single-eval-per-call mode: what one interactive eval costs.

    The production worker picks the solve path by cluster/batch size
    (solver/host.py prefer_host — SURVEY §7.3's latency fallback): a
    small cluster solves with the numpy twin of the kernel in-process
    (identical placements, differential-tested), so a singleton eval
    never pays a device round trip; big clusters keep the device path.
    This benchmark makes the same pick."""
    import numpy as np
    from nomad_tpu.solver.host import HostResidentSolver, prefer_host
    from nomad_tpu.solver.resident import ResidentSolver, STATUS_RETRY

    nodes = make_nodes(n_nodes, devices=config == 4)
    from nomad_tpu.utils.compile_cache import cache_entries
    cache0 = cache_entries()
    t0 = time.perf_counter()
    probe_job = make_job(config, 0, count)
    gp_need = len(probe_job.task_groups)
    kp_need = count
    gp = 1 << max(0, (gp_need - 1).bit_length())
    kp = 1 << max(0, (kp_need - 1).bit_length())
    host = prefer_host(1 << max(0, (n_nodes - 1).bit_length()),
                       gp_need, kp_need)
    if host:
        # no compile-variant reuse to protect on host: exact-size pads
        rs = HostResidentSolver(nodes, asks_for(probe_job),
                                gp=gp_need, kp=kp_need)
    else:
        rs = ResidentSolver(nodes, asks_for(probe_job), gp=gp, kp=kp)
    rs.reset_usage(used0=resident_used0(rs.template, n_nodes, resident))
    jobs = [make_job(config, e, count) for e in range(n_evals)]
    warm = rs.pack_batch(asks_for(jobs[0]))
    rs.solve_stream([warm], seeds=[1])
    rs.reset_usage(used0=resident_used0(rs.template, n_nodes, resident))
    startup_s = time.perf_counter() - t0

    latencies = []
    placed = failed = retried = unresolved = 0
    n_calls = 0
    t_start = time.perf_counter()
    for e, job in enumerate(jobs):
        t_call = time.perf_counter()
        pack = getattr(rs, "pack_batch_cached", rs.pack_batch)
        pb = pack(asks_for(job))
        n_calls += 0 if host else 1     # host mode never leaves the CPU
        _, ok, _, status = rs.solve_stream([pb], seeds=[e + 1])
        placed += int(ok[0, :pb.n_place, 0].sum())
        failed += int((status[0, :pb.n_place] == 0).sum())
        unresolved += int((status[0, :pb.n_place] == STATUS_RETRY).sum())
        latencies.append(time.perf_counter() - t_call)
    elapsed = time.perf_counter() - t_start
    lat = latency_summary(latencies)

    return {
        "engine": ("nomad-tpu host-solver per-eval (latency mode)"
                   if host else
                   "nomad-tpu per-eval device calls (latency mode)"),
        "evals": n_evals, "placements": placed, "failed": failed,
        "retried": retried, "unresolved": unresolved,
        "n_device_calls": n_calls,
        "compile_cache": _cache_report(cache0),
        "elapsed_s": round(elapsed, 4),
        "startup_s": round(startup_s, 2),
        "evals_per_sec": round(n_evals / elapsed, 1),
        "placements_per_sec": round(placed / elapsed, 1),
        "p50_ms": lat["p50_ms"], "p99_ms": lat["p99_ms"],
        "nodes_scored_per_placement": n_nodes,
    }


def run_ours_federated(n_regions, n_nodes, n_evals, count, resident,
                       evals_per_call=128):
    """Config 5: FederatedResidentSolver — every region keeps its own
    node universe and usage tensors, but all regions' stream steps fuse
    into vmapped [R]-stacked device calls (parallel/federated.py): the
    whole federation pays ONE result-fetch round trip.  Steps dispatch
    pipelined (pack step b+1 while step b solves); on a TPU pod the
    region axis shards across chips with no collectives at all."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from nomad_tpu.parallel.federated import FederatedResidentSolver
    from nomad_tpu.solver.kernel import MERGED_GP_MAX
    from nomad_tpu.solver.resident import STATUS_RETRY

    epc = min(evals_per_call, n_evals)
    NB = -(-n_evals // epc)
    probe_job = make_job(5, 0, count)
    # scenario generation (cluster + jobs) happens before the startup
    # clock — parity with run_ours
    region_universe = make_nodes(n_nodes)
    all_jobs = [[make_job(5, r * n_evals + e, count)
                 for e in range(n_evals)] for r in range(n_regions)]
    from nomad_tpu.utils.compile_cache import cache_entries
    cache0 = cache_entries()
    t0 = time.perf_counter()
    # one shared universe across regions: the federated solver packs
    # it once (usage tensors stay per-region).  gp sized to the real
    # distinct-signature count (see run_ours) — config 5's merged
    # stream needs 1 row, not MERGED_GP_MAX
    from nomad_tpu.solver.tensorize import Tensorizer
    gp_need = len({Tensorizer.ask_signature(a)
                   for a in asks_for(probe_job)})
    fed = FederatedResidentSolver(
        [region_universe] * n_regions,
        asks_for(probe_job), gp=1 << max(0, (gp_need - 1).bit_length()),
        kp=1 << max(0, (count * epc - 1).bit_length()), max_waves=18)
    used0_region = resident_used0(fed.solvers[0].template, n_nodes,
                                  resident)
    used0 = np.stack([used0_region] * n_regions)

    # pipelined per-step dispatch (see run_ours): pack step b for all
    # regions, dispatch that one [R]-vmapped step as a chained call,
    # pack step b+1 while it solves; ONE concatenated fetch at the end
    import jax
    wasks, _wk = fed.merge_asks(0, sum(
        (asks_for(make_job(5, 9000 + e, count)) for e in range(epc)), []))
    warm = fed.pack_batch(0, wasks)
    warm.job_keys = None
    concat_jit = jax.jit(lambda *xs: jnp.concatenate(xs))
    wouts = [fed.solve_stream_async([[warm]] * n_regions,
                                    seeds=[[b + 1]] * n_regions)
             for b in range(NB)]
    np.asarray(concat_jit(*wouts))
    fed.reset_usage(used0=used0)
    startup_s = time.perf_counter() - t0

    t_start = time.perf_counter()
    batches = [[] for _ in range(n_regions)]
    outs = []
    pack_s = dispatch_s = 0.0
    for b in range(NB):
        i = b * epc
        step = []
        t_p = time.perf_counter()
        for r in range(n_regions):
            masks, mkeys = fed.merge_asks(r, sum(
                (asks_for(j) for j in all_jobs[r][i:i + epc]), []))
            pb = fed.pack_batch_cached(r, masks, job_keys=mkeys)
            batches[r].append(pb)
            step.append([pb])
        t_d = time.perf_counter()
        outs.append(fed.solve_stream_async(
            step, seeds=[[r * NB + b + 1] for r in range(n_regions)]))
        t_e = time.perf_counter()
        pack_s += t_d - t_p
        dispatch_s += t_e - t_d
    packed = np.asarray(concat_jit(*outs))            # ONE fetch
    elapsed = time.perf_counter() - t_start
    status = packed[:, :, :, -1].astype(np.int32)     # [NB, R, K]

    # steady-state delta waves: the same region-fused steps
    # re-dispatched — the step-level device cache ships nothing
    n_steady = min(4, NB)
    t_s = time.perf_counter()
    souts = [fed.solve_stream_async(
        [[batches[r][b]] for r in range(n_regions)],
        seeds=[[9000 + r * NB + b] for r in range(n_regions)])
        for b in range(n_steady)]
    t_sd = time.perf_counter()
    np.asarray(concat_jit(*souts) if n_steady > 1 else souts[0])
    main_pd = (pack_s + dispatch_s) / max(NB, 1)
    steady_pd = (t_sd - t_s) / n_steady
    steady = {
        "waves": n_steady,
        "steady_pack_dispatch_ms_per_wave": round(1000 * steady_pd, 3),
        "first_pass_pack_dispatch_ms_per_wave": round(1000 * main_pd, 3),
        "pack_dispatch_reduction": round(main_pd / max(steady_pd, 1e-9),
                                         1),
        "elapsed_s": round(time.perf_counter() - t_s, 4),
    }

    placed = failed = unresolved = 0
    for r in range(n_regions):
        for b, pb in enumerate(batches[r]):
            st = status[b, r, :pb.n_place]
            placed += int((st == 1).sum())
            failed += int((st == 0).sum())
            unresolved += int((st == STATUS_RETRY).sum())
    total_evals = n_regions * n_evals
    return {
        "engine": f"nomad-tpu federated stream x{n_regions} regions, "
                  "region-fused device calls",
        "evals": total_evals, "placements": placed, "failed": failed,
        "retried": 0, "unresolved": unresolved,
        "n_device_calls": 1,
        "breakdown_ms": {
            "pack": round(1000 * pack_s, 1),
            "dispatch": round(1000 * dispatch_s, 1),
        },
        "steady_state": steady,
        "compile_cache": _cache_report(cache0),
        "elapsed_s": round(elapsed, 4),
        "startup_s": round(startup_s, 2),
        "evals_per_sec": round(total_evals / elapsed, 1),
        "placements_per_sec": round(placed / elapsed, 1),
        # single fused call: every eval completes with the one fetch
        **latency_summary([elapsed]),
        "nodes_scored_per_placement": n_nodes,
    }


# ---------------- denominator: stock C++ engine ----------------------

def ensure_stock_engine():
    if (not os.path.exists(STOCK_BIN)
            or os.path.getmtime(STOCK_BIN) < os.path.getmtime(STOCK_SRC)):
        subprocess.run(["g++", "-O2", "-std=c++17", "-o", STOCK_BIN,
                        STOCK_SRC], check=True)


def run_stock(config, n_nodes, n_evals, count, resident, gen_seed=0):
    ensure_stock_engine()
    out = subprocess.run(
        [STOCK_BIN, str(config), str(n_nodes), str(n_evals), str(count),
         str(resident), str(gen_seed)],
        check=True, capture_output=True, text=True).stdout
    return json.loads(out)


# ---------------- configs ----------------

CONFIGS = {
    # n_evals sizes each steady-state workload to roughly 60-70% of the
    # cluster's REMAINING capacity: long enough that fixed costs
    # amortize on both engines, short of the pathological full-cluster
    # regime where every placement fails.  Configs 4 and 5 carry the
    # same resident-alloc load as the others (BASELINE measures loaded
    # 10K-node clusters, not empty ones); both engines see identical
    # generated clusters either way.
    1: dict(n_nodes=100, n_evals=12, count=100, resident=0),
    2: dict(n_nodes=10_000, n_evals=1536, count=64, resident=50_000),
    3: dict(n_nodes=10_000, n_evals=896, count=64, resident=100_000),
    4: dict(n_nodes=10_000, n_evals=1536, count=16, resident=50_000),
    5: dict(n_nodes=10_000, n_evals=768, count=64, resident=50_000),
}


def run_config(config):
    import gc
    p = CONFIGS[config]
    # the tunneled transport's throughput swings +-30-50% run to run;
    # best-of-3 on both engines keeps the recorded numbers stable —
    # identical treatment on both sides
    if config == 1:
        runner = lambda: run_ours_latency(config, **p)  # noqa: E731
    elif config == 5:
        runner = lambda: run_ours_federated(4, **p)     # noqa: E731
    else:
        runner = lambda: run_ours(config, **p)          # noqa: E731

    def one_trial():
        gc.collect()          # drop prior trials' device buffers
        return runner()

    trials = [one_trial() for _ in range(3)]
    ours = min(trials, key=lambda r: r["elapsed_s"])
    # startup and elapsed are independent samples: trial 1 pays the
    # one-time device program load (cold attach), later trials restart
    # against the already-loaded program (the failover-relevant cost).
    # Record both.
    ours["startup_s"] = min(t["startup_s"] for t in trials)
    ours["startup_cold_s"] = max(t["startup_s"] for t in trials)
    stock = min((run_stock(config, **p) for _ in range(3)),
                key=lambda r: r["elapsed_s"])
    ratio_p = (ours["placements_per_sec"] / stock["placements_per_sec"]
               if stock["placements_per_sec"] else float("inf"))
    ratio_e = (ours["evals_per_sec"] / stock["evals_per_sec"]
               if stock["evals_per_sec"] else float("inf"))
    return {"config": config, "params": p, "ours": ours, "stock": stock,
            "ratio_placements": round(ratio_p, 3),
            "ratio_evals": round(ratio_e, 3)}


def run_quality_duel(config=3, n_nodes=512, count=64, load=1.15,
                     gen_seed=0):
    """Pack-to-capacity: same over-subscribed workload on both engines;
    the engine with better bin-packing places more before exhaustion.
    Stock ranks max(2, log2 N) sampled nodes per placement; the solve
    scores all N. Config 3's mixed ask sizes (400-850 cpu) make
    fragmentation matter."""
    # capacity estimate per config shape: cpu-bound for plain/mixed
    # asks, device-bound for config 4 (1 device/placement, 8 per
    # device-bearing node, every 2nd node)
    if config == 4:
        cap = (n_nodes // 2) * 8
    else:
        avg_ask = 625 if config == 3 else 400
        cap = int(n_nodes * (7500 / avg_ask))
    n_evals = max(1, int(cap * load) // count)
    # quality mode: one eval per call, exact deterministic scoring (the
    # production single-eval path) - no throughput-mode jitter/offsets
    ours = run_ours(config, n_nodes=n_nodes, n_evals=n_evals,
                    count=count, resident=0, evals_per_call=1,
                    exact=True, gen_seed=gen_seed)
    stock = run_stock(config, n_nodes=n_nodes, n_evals=n_evals,
                      count=count, resident=0, gen_seed=gen_seed)
    return {
        "config": config, "load": load, "gen_seed": gen_seed,
        "workload_placements": n_evals * count,
        "capacity_estimate": cap,
        "ours_placed": ours["placements"],
        "stock_placed": stock["placements"],
        "placed_ratio": round(
            ours["placements"] / max(stock["placements"], 1), 4),
    }


def run_quality_sweep(seeds=(0, 1, 2, 3, 4)):
    """Multi-seed, multi-shape, multi-load pack-to-capacity sweep
    (VERDICT r4 item 3: one seed/one config is a tie, not a win).
    Returns per-duel records + mean/min placed_ratio."""
    duels = []
    for config in (2, 3, 4):
        for load in (0.95, 1.15):
            for seed in seeds:
                duels.append(run_quality_duel(
                    config=config, load=load, gen_seed=seed))
                sys.stderr.write(
                    f"quality duel config={config} load={load} "
                    f"seed={seed}: {duels[-1]['placed_ratio']}\n")
    ratios = [d["placed_ratio"] for d in duels]
    return {
        "duels": duels,
        "n": len(duels),
        "mean_placed_ratio": round(sum(ratios) / len(ratios), 4),
        "min_placed_ratio": min(ratios),
        "max_placed_ratio": max(ratios),
    }


# ---------------- overcommit: in-kernel preemption (ISSUE 7) --------

def _oc_fill_job(i, rng):
    """A low-priority background job for the overcommit fill tier."""
    from nomad_tpu import mock
    job = mock.job(priority=int(rng.choice([5, 10, 20, 30, 45])))
    job.id = f"fill-{i}"
    job.name = job.id
    job.datacenters = [f"dc{d}" for d in range(4)]
    job.constraints = []
    tg = job.task_groups[0]
    tg.constraints = []
    tg.count = 16
    t = tg.tasks[0]
    t.resources.networks = []
    t.resources.cpu = int(rng.choice([400, 700, 900, 1200]))
    t.resources.memory_mb = t.resources.cpu
    tg.ephemeral_disk.size_mb = 100
    tg.networks = []
    return job


def _oc_eligible(config, nodes):
    """Nodes the config's HIGH-priority job shape can land on — the
    load multiple is defined over this subset's capacity (config 3
    excludes its constraint-filtered nodes, config 4 is device-bound)."""
    if config == 3:
        return [n for n in nodes if n.attributes["rack"] != "r63"
                and n.attributes["zone"] >= "z1"]
    if config == 4:
        return [n for n in nodes if n.node_resources.devices]
    return nodes


def _overcommit_leg(config, n_nodes, load, evict_e, gen_seed=0,
                    fill=0.8, count=16):
    """One scheduler-level overcommit leg: fill the cluster with
    low-priority running allocs to ~`fill` of cpu capacity, then drive
    priority-70 jobs through the REAL scheduler stack (Harness +
    store-attached resident Solver, preemption enabled) until total
    demand reaches `load` x eligible capacity.

    `evict_e` > 0 packs the evictable-alloc planes, so eviction sets
    are selected by the in-kernel preemption waves; `evict_e` = 0
    disables the planes and every exhausted placement takes the
    host-side preemption walk (`_try_preemption`) — the pre-ISSUE-7
    fallback this phase compares against.  Same store, same scheduler,
    same solve path otherwise."""
    from nomad_tpu import mock, structs as _st
    from nomad_tpu.scheduler.harness import Harness
    from nomad_tpu.solver.solve import Solver
    from nomad_tpu.state.store import SchedulerConfiguration
    from nomad_tpu.utils.metrics import global_metrics
    import numpy as np

    prev = os.environ.get("NOMAD_TPU_EVICT_E")
    os.environ["NOMAD_TPU_EVICT_E"] = str(evict_e)
    try:
        rng = np.random.default_rng(gen_seed * 31 + config)
        h = Harness()
        h.store.set_scheduler_config(
            h.next_index(),
            SchedulerConfiguration(preemption_service=True))
        nodes = make_nodes(n_nodes, devices=(config == 4),
                           gen_seed=gen_seed)
        for n in nodes:
            h.store.upsert_node(h.next_index(), n)
        h.solver = Solver(store=h.store, resident_min_nodes=1)
        elig = _oc_eligible(config, nodes)
        elig_ids = {n.id for n in elig}
        cap_cpu = float(sum(n.node_resources.cpu for n in elig))
        total_cpu = float(sum(n.node_resources.cpu for n in nodes))

        # ---- fill tier: bin-packed low-priority allocs, marked RUNNING
        filled = 0.0
        fill_elig = 0.0
        misses = 0
        i = 0
        while filled < fill * total_cpu and misses < 3:
            job = _oc_fill_job(i, rng)
            h.store.upsert_job(h.next_index(), job)
            h.process("service", mock.eval_(
                job_id=job.id,
                triggered_by=_st.EVAL_TRIGGER_JOB_REGISTER))
            allocs = h.store.allocs_by_job("default", job.id)
            for a in allocs:
                a.client_status = _st.ALLOC_CLIENT_RUNNING
            if allocs:
                h.store.upsert_allocs(h.next_index(), allocs)
                cpu = job.task_groups[0].tasks[0].resources.cpu
                filled += cpu * len(allocs)
                fill_elig += cpu * sum(a.node_id in elig_ids
                                       for a in allocs)
                misses = 0
            else:
                misses += 1
            i += 1

        # ---- high tier: measured sweep to load x eligible capacity
        per_place = 625 if config == 3 else 400
        high_cpu = max(0.0, load * cap_cpu - fill_elig)
        n_evals = max(1, int(round(high_cpu / (per_place * count))))
        global_metrics.reset()
        plans0 = len(h.plans)
        lat = []
        t0 = time.perf_counter()
        for e in range(n_evals):
            job = make_job(config if config != 5 else 2, e, count,
                           gen_seed)
            job.id = f"hi-{config}-{e}"
            job.name = job.id
            job.priority = 70
            if config == 5:
                # federation shape: each job pinned to one region(dc)
                job.datacenters = [f"dc{e % 4}"]
            h.store.upsert_job(h.next_index(), job)
            ts = time.perf_counter()
            h.process("service", mock.eval_(
                job_id=job.id, priority=70,
                triggered_by=_st.EVAL_TRIGGER_JOB_REGISTER))
            lat.append(time.perf_counter() - ts)
        wall = time.perf_counter() - t0
        evictions = placed = 0
        for p in h.plans[plans0:]:
            evictions += sum(len(v) for v in p.node_preemptions.values())
            placed += sum(len(v) for v in p.node_allocation.values())
        counters = global_metrics.dump().get("counters", {})
        kern = int(counters.get("scheduler.preempt.kernel", 0))
        fb = int(counters.get("scheduler.preempt.host_fallback", 0))
        return {
            "mode": "kernel" if evict_e > 0 else "host_walk",
            "config": config, "load": load, "n_nodes": n_nodes,
            "n_evals": n_evals, "count": count,
            "fill_frac": round(filled / total_cpu, 3),
            "wall_s": round(wall, 3),
            "evals_per_sec": round(n_evals / wall, 2),
            "placements": placed,
            "evictions": evictions,
            "evictions_per_sec": round(evictions / wall, 1),
            "preempt_kernel": kern,
            "preempt_host_fallback": fb,
            "fast_path_retention_pct": round(
                100.0 * kern / max(kern + fb, 1), 2),
            **latency_summary(lat),
        }
    finally:
        if prev is None:
            os.environ.pop("NOMAD_TPU_EVICT_E", None)
        else:
            os.environ["NOMAD_TPU_EVICT_E"] = prev


def _verify_twin_identity(gen_seed=0, n_nodes=64, count=16):
    """(place, evict) bit-identity of the device eviction pass vs the
    host twin on THIS phase's workload shape — a spot check riding the
    bench; the full pallas x shortlist x shard matrix is tier-1
    (tests/test_preempt_kernel.py)."""
    import numpy as np
    from nomad_tpu import mock
    from nomad_tpu.parallel.sharded import kernel_args
    from nomad_tpu.solver.host import host_solve_kernel
    from nomad_tpu.solver.kernel import solve_kernel
    from nomad_tpu.solver.tensorize import (Tensorizer,
                                            alloc_usage_vector)

    rng = np.random.default_rng(gen_seed + 7)
    nodes = make_nodes(n_nodes, gen_seed=gen_seed)
    for n in nodes:
        # tight nodes so the asks below genuinely need evictions
        n.node_resources.cpu = int(rng.choice([3000, 4000, 6000]))
        n.compute_class()
    abn = {}
    ci = 0
    for i, n in enumerate(nodes):
        lst = []
        for k in range(int(rng.integers(2, 6))):
            a = mock.alloc()
            a.id = f"low-{i}-{k}"
            a.node_id = n.id
            a.job.priority = int(rng.choice([5, 10, 20, 30, 45]))
            a.create_index = ci
            tr = a.allocated_resources.tasks["web"]
            tr.cpu = int(rng.choice([400, 700, 900, 1200]))
            tr.memory_mb, tr.networks = tr.cpu, []
            a.allocated_resources.shared.networks = []
            a.allocated_resources.shared.disk_mb = 0
            lst.append(a)
            ci += 1
        abn[n.id] = lst
    job = make_job(3, 0, count, gen_seed)
    job.priority = 70
    for tg in job.task_groups:
        tg.count = count
        tg.tasks[0].resources.cpu = 2000
        tg.tasks[0].resources.memory_mb = 2048
    pb = Tensorizer().pack(nodes, asks_for(job), abn, evict_e=8)
    used0 = np.zeros_like(pb.used0)
    for i, n in enumerate(nodes):
        for a in abn[n.id]:
            used0[i] += alloc_usage_vector(a)
    pb.used0 = used0
    ev_kw = dict(has_preempt=True, ev_res=pb.ev_res, ev_prio=pb.ev_prio,
                 ask_prio=pb.ask_prio)
    host = host_solve_kernel(*kernel_args(pb), **ev_kw)
    res = solve_kernel(*kernel_args(pb), has_distinct=False, **ev_kw)
    ok = np.asarray(res.choice_ok)
    same = (np.array_equal(ok, host.choice_ok)
            and np.array_equal(np.where(ok, np.asarray(res.choice), -1),
                               np.where(host.choice_ok, host.choice, -1))
            and np.array_equal(np.asarray(res.evict),
                               np.asarray(host.evict)))
    return {"n_nodes": n_nodes,
            "evict_pairs": int(np.asarray(host.evict).any(axis=1).sum()),
            "identical": bool(same)}


def run_overcommit(n_nodes=128, count=16, fill=0.8,
                   loads=(1.0, 1.15, 1.3, 1.5), gen_seed=0,
                   write_detail=True):
    """Overcommit phase (ISSUE 7 acceptance).

    Load sweep 1.0x-1.5x on the primary config (3) comparing the
    in-kernel preemption waves against the host-side preemption walk
    (`NOMAD_TPU_EVICT_E=0` — the pre-ISSUE-7 path), then the
    acceptance cell at load 1.15 on configs 3-5: zero host-side
    fallbacks (fast-path retention 100%), >= 1.3x wall-clock vs the
    host walk, evictions > 0, and a (place, evict) twin-identity spot
    check.  Scheduler-level end to end: real store, real
    GenericScheduler, store-attached resident Solver."""
    out = {"phase": "overcommit", "n_nodes": n_nodes, "count": count,
           "fill": fill, "sweep": [], "acceptance_configs": {}}

    def duel(config, load):
        k = _overcommit_leg(config, n_nodes, load, evict_e=8,
                            gen_seed=gen_seed, fill=fill, count=count)
        hw = _overcommit_leg(config, n_nodes, load, evict_e=0,
                             gen_seed=gen_seed, fill=fill, count=count)
        speed = round(hw["wall_s"] / max(k["wall_s"], 1e-9), 2)
        sys.stderr.write(
            f"overcommit config={config} load={load}: kernel "
            f"{k['wall_s']}s ({k['evictions']} ev, "
            f"retention {k['fast_path_retention_pct']}%) vs host walk "
            f"{hw['wall_s']}s -> {speed}x\n")
        return {"config": config, "load": load, "kernel": k,
                "host_walk": hw, "speedup_wall": speed}

    for load in loads:
        out["sweep"].append(duel(3, load))

    ok = True
    for config in (3, 4, 5):
        rec = (next(r for r in out["sweep"] if r["load"] == 1.15)
               if config == 3 and 1.15 in loads else duel(config, 1.15))
        k, hw = rec["kernel"], rec["host_walk"]
        acc = {
            "load": 1.15,
            "evictions": k["evictions"],
            "evictions_per_sec": k["evictions_per_sec"],
            "zero_host_fallbacks": k["preempt_host_fallback"] == 0,
            "fast_path_retention_pct": k["fast_path_retention_pct"],
            "speedup_vs_host_walk": rec["speedup_wall"],
            "speedup_ge_1_3": rec["speedup_wall"] >= 1.3,
            "p99_ms_kernel": k["p99_ms"],
            "p99_ms_host_walk": hw["p99_ms"],
        }
        out["acceptance_configs"][str(config)] = acc
        ok = ok and (acc["zero_host_fallbacks"] and acc["speedup_ge_1_3"]
                     and k["evictions"] > 0)
    out["twin_identity"] = _verify_twin_identity(gen_seed)
    ok = ok and out["twin_identity"]["identical"]
    out["ok"] = bool(ok)
    if write_detail:
        path = os.path.join(REPO, "BENCH_DETAIL.json")
        try:
            with open(path) as f:
                detail = json.load(f)
        except (OSError, json.JSONDecodeError):
            detail = {}
        detail["overcommit"] = out
        with open(path, "w") as f:
            json.dump(detail, f, indent=1)
    return out


def lint_summary():
    """nomadlint state for this run (analyzer version + finding
    counts), recorded in BENCH_DETAIL so every benchmark carries the
    lint state it was measured under."""
    try:
        from nomad_tpu.analysis import ANALYZER_VERSION, analyze, \
            pass_of
        t0 = time.perf_counter()
        rep = analyze()
        wall_s = round(time.perf_counter() - t0, 2)
        baselined_by_pass = {}
        for f in rep.suppressed:
            p = pass_of(f.rule)
            baselined_by_pass[p] = baselined_by_pass.get(p, 0) + 1
        out = {"version": ANALYZER_VERSION,
               "wall_s": wall_s,
               "unsuppressed": len(rep.findings),
               "errors": len(rep.errors),
               "warnings": len(rep.warnings),
               "baselined": len(rep.suppressed),
               "stale_baseline_keys": rep.stale_baseline_keys,
               "by_rule": rep.counts_by_rule(),
               "by_pass": rep.counts_by_pass(),
               "baselined_by_pass": dict(sorted(
                   baselined_by_pass.items()))}
    except Exception as e:          # never lose the run over lint
        out = {"error": str(e)}
    try:
        # scoring-spec provenance: which spec version (and term list)
        # every backend was verified against when this run was taken
        from nomad_tpu.solver import score_spec
        out["score_spec"] = {"version": score_spec.SPEC_VERSION,
                             "terms": list(score_spec.term_names())}
    except Exception:
        pass
    try:
        # flight-recorder shape for this run (ISSUE 10): the startup
        # line + BENCH_DETAIL record what the trace ring could hold
        from nomad_tpu.utils.tracing import global_tracer
        st = global_tracer.stats()
        out["trace_store"] = {"depth": st["depth_limit"],
                              "enabled": st["enabled"]}
    except Exception:
        pass
    return out


def main():
    if len(sys.argv) > 2 and sys.argv[1] == "--one":
        # subprocess mode: run one config, print its record as JSON
        print("\x1e" + json.dumps(run_config(int(sys.argv[2]))))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--multichip":
        # subprocess mode: the mesh-resident multichip phase (writes
        # MULTICHIP_DETAIL.json, prints the record) — isolated because
        # it may clear backends to self-provision virtual devices
        out = run_multichip()
        print("\x1e" + json.dumps(out))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--multiregion":
        # subprocess mode: the WAN federation phase (ISSUE 13) —
        # merges its record into MULTICHIP_DETAIL.json, prints it
        out = run_multiregion()
        print("\x1e" + json.dumps(out))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--chaos":
        # subprocess mode: the chaos storm phase (ISSUE 14) — merges
        # its record into BENCH_DETAIL.json under "chaos"; isolated
        # because it self-provisions virtual devices and arms
        # process-wide injection/watchdog state
        out = run_chaos()
        print("\x1e" + json.dumps(out))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--open-loop":
        # subprocess mode: the open-loop serving phase (ISSUE 6) —
        # merges its record into BENCH_DETAIL.json under "open_loop"
        out = run_open_loop()
        print("\x1e" + json.dumps(out))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--scaleout":
        # subprocess mode: the scale-out control-plane phase (ISSUE 17)
        # — merges its record into BENCH_DETAIL.json under "scaleout"
        out = run_scaleout()
        print("\x1e" + json.dumps(out))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--overcommit":
        # subprocess mode: the in-kernel preemption phase (ISSUE 7) —
        # merges its record into BENCH_DETAIL.json under "overcommit"
        out = run_overcommit()
        print("\x1e" + json.dumps(out))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--tracing":
        # subprocess mode: the tracing-overhead phase (ISSUE 10) —
        # merges its record into BENCH_DETAIL.json under
        # "tracing_overhead"
        out = run_tracing_overhead()
        print("\x1e" + json.dumps(out))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--telemetry":
        # subprocess mode: the health-kernel/fragmentation phase
        # (ISSUE 15) — merges its record into BENCH_DETAIL.json under
        # "telemetry"
        out = run_telemetry_overhead()
        print("\x1e" + json.dumps(out))
        return
    if len(sys.argv) > 1 and sys.argv[1] == "--quality-sweep":
        out = run_quality_sweep()
        with open(os.path.join(REPO, "QUALITY_SWEEP.json"), "w") as f:
            json.dump(out, f, indent=1)
        print(json.dumps({k: out[k] for k in
                          ("n", "mean_placed_ratio", "min_placed_ratio",
                           "max_placed_ratio")}))
        return
    only = int(sys.argv[1]) if len(sys.argv) > 1 else None
    # lint state up front so BENCH_DETAIL records which invariants held
    # for this run (pure-AST pass, no device; never blocks the bench)
    lint = lint_summary()
    sys.stderr.write(
        f"nomadlint v{lint.get('version', '?')}: "
        f"{lint.get('unsuppressed', '?')} unsuppressed, "
        f"{lint.get('baselined', '?')} baselined"
        + (f" ({lint['error']})" if "error" in lint else "")
        + (f"; trace-store depth "
           f"{lint['trace_store']['depth']}"
           + ("" if lint['trace_store']['enabled'] else " (off)")
           if "trace_store" in lint else "") + "\n")
    results = []
    for c in sorted(CONFIGS):
        if only and c != only:
            continue
        if only:
            results.append(run_config(c))
            continue
        # full-suite mode: one subprocess per config — isolates device
        # state and the transport client between configs (long-lived
        # processes showed config-order throughput drift) while the
        # persistent XLA compile cache keeps per-config startup warm
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--one", str(c)],
            capture_output=True, text=True)
        rec = None
        # a teardown crash AFTER the record printed must not discard
        # the measurement (r3 ran config 5 twice for this reason):
        # trust the record line regardless of exit code
        for line in out.stdout.splitlines():
            if line.startswith("\x1e"):
                try:
                    rec = json.loads(line[1:])
                except json.JSONDecodeError:
                    rec = None
        if out.returncode != 0:
            sys.stderr.write(
                f"config {c} subprocess exited {out.returncode} "
                f"({'record salvaged' if rec else 'no record'}):\n"
                f"{out.stdout[-1500:]}\n{out.stderr[-1500:]}\n")
        if rec is None:
            rec = run_config(c)        # in-process fallback
        results.append(rec)
    rtt = measure_transport_rtt()
    for r in results:
        if r["config"] == 1:
            continue    # latency mode measures the round trip by design
        o = r["ours"]
        if "n_device_calls" in o:
            compute_s = max(o["elapsed_s"] - o["n_device_calls"] * rtt,
                            1e-6)
            o["projected_local_attach_placements_per_sec"] = round(
                o["placements"] / compute_s, 1)
            r["ratio_placements_projected"] = round(
                o["projected_local_attach_placements_per_sec"]
                / max(r["stock"]["placements_per_sec"], 1e-9), 3)
    # multichip phase (ISSUE 5) in its own subprocess: it may clear
    # backends to self-provision an 8-device virtual platform, which
    # must not disturb the transport client the configs above used.
    # The phase self-provisions, so device_count()==1 is NOT a skip.
    multichip = None
    mp_env = dict(os.environ)
    mp_env["JAX_PLATFORMS"] = "cpu"
    mp_env["XLA_FLAGS"] = (
        mp_env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()
    mp = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multichip"],
        capture_output=True, text=True, env=mp_env)
    for line in mp.stdout.splitlines():
        if line.startswith("\x1e"):
            try:
                multichip = json.loads(line[1:])
            except json.JSONDecodeError:
                multichip = None
    if multichip is None:
        multichip = {"phase": "multichip", "skipped": True,
                     "rc": mp.returncode,
                     "tail": (mp.stderr or mp.stdout)[-1500:]}
        sys.stderr.write(
            f"multichip phase failed rc={mp.returncode}:\n"
            f"{(mp.stderr or '')[-1500:]}\n")
    # multi-region WAN federation phase (ISSUE 13): same forced
    # 8-device virtual platform as multichip, run AFTER it so the
    # record merges into the MULTICHIP_DETAIL.json it just wrote
    multiregion = None
    mr = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multiregion"],
        capture_output=True, text=True, env=mp_env)
    for line in mr.stdout.splitlines():
        if line.startswith("\x1e"):
            try:
                multiregion = json.loads(line[1:])
            except json.JSONDecodeError:
                multiregion = None
    if multiregion is None:
        multiregion = {"phase": "multiregion", "skipped": True,
                       "rc": mr.returncode,
                       "tail": (mr.stderr or mr.stdout)[-1500:]}
        sys.stderr.write(
            f"multiregion phase failed rc={mr.returncode}:\n"
            f"{(mr.stderr or '')[-1500:]}\n")
    # open-loop serving phase (ISSUE 6) in its own subprocess: it
    # drives threads + a large broker population and must not perturb
    # the configs' device state; the record is also self-merged into
    # BENCH_DETAIL.json, but carrying it in `detail` keeps one write
    open_loop = None
    ol = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--open-loop"],
        capture_output=True, text=True)
    for line in ol.stdout.splitlines():
        if line.startswith("\x1e"):
            try:
                open_loop = json.loads(line[1:])
            except json.JSONDecodeError:
                open_loop = None
    if open_loop is None:
        open_loop = {"phase": "open_loop", "skipped": True,
                     "rc": ol.returncode,
                     "tail": (ol.stderr or ol.stdout)[-1500:]}
        sys.stderr.write(
            f"open-loop phase failed rc={ol.returncode}:\n"
            f"{(ol.stderr or '')[-1500:]}\n")
    # scale-out control-plane phase (ISSUE 17) in its own subprocess:
    # it runs worker/coordinator thread fleets over a resident world
    # and must not perturb the configs' device state; self-merged into
    # BENCH_DETAIL.json too
    scaleout = None
    so = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--scaleout"],
        capture_output=True, text=True)
    for line in so.stdout.splitlines():
        if line.startswith("\x1e"):
            try:
                scaleout = json.loads(line[1:])
            except json.JSONDecodeError:
                scaleout = None
    if scaleout is None:
        scaleout = {"phase": "scaleout", "skipped": True,
                    "rc": so.returncode,
                    "tail": (so.stderr or so.stdout)[-1500:]}
        sys.stderr.write(
            f"scaleout phase failed rc={so.returncode}:\n"
            f"{(so.stderr or '')[-1500:]}\n")
    # overcommit / in-kernel preemption phase (ISSUE 7) in its own
    # subprocess: it drives the full scheduler stack over a store and
    # toggles NOMAD_TPU_EVICT_E between legs
    overcommit = None
    oc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--overcommit"],
        capture_output=True, text=True)
    for line in oc.stdout.splitlines():
        if line.startswith("\x1e"):
            try:
                overcommit = json.loads(line[1:])
            except json.JSONDecodeError:
                overcommit = None
    if overcommit is None:
        overcommit = {"phase": "overcommit", "skipped": True,
                      "rc": oc.returncode,
                      "tail": (oc.stderr or oc.stdout)[-1500:]}
        sys.stderr.write(
            f"overcommit phase failed rc={oc.returncode}:\n"
            f"{(oc.stderr or '')[-1500:]}\n")
    # tracing-overhead phase (ISSUE 10) in its own subprocess: it
    # builds a config-3-scale resident world and must not disturb the
    # configs' device state; self-merged into BENCH_DETAIL.json too
    tracing = None
    tr = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--tracing"],
        capture_output=True, text=True)
    for line in tr.stdout.splitlines():
        if line.startswith("\x1e"):
            try:
                tracing = json.loads(line[1:])
            except json.JSONDecodeError:
                tracing = None
    if tracing is None:
        tracing = {"phase": "tracing_overhead", "skipped": True,
                   "rc": tr.returncode,
                   "tail": (tr.stderr or tr.stdout)[-1500:]}
        sys.stderr.write(
            f"tracing phase failed rc={tr.returncode}:\n"
            f"{(tr.stderr or '')[-1500:]}\n")
    # telemetry phase (ISSUE 15) in its own subprocess: same config-3
    # scale resident world as tracing; measures the health kernel's
    # steady-state cost and the churn fragmentation trajectory
    telemetry = None
    tm = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--telemetry"],
        capture_output=True, text=True)
    for line in tm.stdout.splitlines():
        if line.startswith("\x1e"):
            try:
                telemetry = json.loads(line[1:])
            except json.JSONDecodeError:
                telemetry = None
    if telemetry is None:
        telemetry = {"phase": "telemetry", "skipped": True,
                     "rc": tm.returncode,
                     "tail": (tm.stderr or tm.stdout)[-1500:]}
        sys.stderr.write(
            f"telemetry phase failed rc={tm.returncode}:\n"
            f"{(tm.stderr or '')[-1500:]}\n")
    detail = {"configs": results,
              "transport_rtt_ms": round(1000 * rtt, 1),
              "multichip": multichip,
              "multiregion": multiregion,
              "open_loop": open_loop,
              "scaleout": scaleout,
              "overcommit": overcommit,
              "tracing_overhead": tracing,
              "telemetry": telemetry,
              "lint": lint}
    if only is None:
        # multi-seed / multi-shape / both-load sweep (30 duels): the
        # quality claim must be systematic, not one lucky seed.  The
        # classic headline duel is the sweep's (config 3, 1.15, seed 0)
        # cell — reuse it rather than run a 31st duel
        # applier saturation: the plan pipeline must not serialize on
        # the consensus round trip (VERDICT r4 item 5)
        import importlib.util as _ilu
        _spec = _ilu.spec_from_file_location(
            "applier_bench", os.path.join(REPO, "bench",
                                          "applier_bench.py"))
        _ab = _ilu.module_from_spec(_spec)
        _spec.loader.exec_module(_ab)
        detail["applier_pipeline"] = _ab.run_applier_bench(3.0)
        # device-only ceiling + roofline for the primary config
        try:
            detail["device_ceiling"] = measure_device_ceiling(3)
        except Exception as e:      # never lose the run over analysis
            detail["device_ceiling"] = {"error": str(e)}
        sweep = run_quality_sweep()
        detail["quality_sweep"] = sweep
        detail["quality_pack_to_capacity"] = next(
            (d for d in sweep["duels"]
             if d["config"] == 3 and d["load"] == 1.15
             and d["gen_seed"] == 0), sweep["duels"][0])
        detail["notes"] = [
            "denominator: bench/stock_engine.cc — reference semantics "
            "(subsampled ranking, class-memoized feasibility, serial "
            "re-validating applier) in C++ at Go-comparable speed, fed "
            "the identical generated cluster/jobs",
            "the denominator is an UPPER BOUND on the reference's "
            "throughput: it keeps state in flat hash tables and skips "
            "the reference's memdb radix indexes, msgpack plan "
            "serialization, RPC hops and disk writes — real deployed "
            "schedulers run the same semantics considerably slower",
            "numerator timings include ask packing, transfer, solve and "
            "result fetch; one-time startup (node pack + device_put + "
            "XLA compile) reported separately as startup_s",
            "numerator runs over a tunneled TPU transport with a fixed "
            "~100ms round trip per device call; local-attached TPU "
            "dispatch is ~100x lower latency",
            "per-config ours.steady_state reports the DELTA-WAVE regime "
            "(ISSUE 2): the same eval population re-dispatched with a "
            "plan-apply usage changeset applied between waves — "
            "pack_dispatch_reduction compares first-pass vs steady "
            "per-wave pack+dispatch ms; ours.delta_counters carries "
            "delta_applies / repack_fallbacks / last_delta_ratio / "
            "bytes_dispatched_delta vs bytes_dispatched_full, and "
            "ours.compile_cache the persistent-XLA-cache hit/miss of "
            "this startup (warm_start = no new compiles persisted)",
            "numerator THROUGHPUT mode merges identical stateless asks "
            "at pack time (summed counts; distinct_hosts and stateful "
            "asks never merge) — the columnar payoff of coalescing "
            "evals; job-scoped soft scoring is then computed over the "
            "merged population while hard commit quotas stay exact. "
            "The quality duel runs EXACT mode (no merging, no jitter)",
        ]
        with open(os.path.join(REPO, "BENCH_DETAIL.json"), "w") as f:
            json.dump(detail, f, indent=1)
    primary = next((r for r in results if r["config"] == 3), results[0])
    # ALL five configs count: 1 is interactive latency (native in-
    # process solve), 2-5 are throughput streams — r4 verdict item 2
    ratios = [r["ratio_placements"] for r in results]
    geomean = (math.exp(sum(math.log(max(r, 1e-9)) for r in ratios)
                        / len(ratios)) if ratios else None)
    print(json.dumps({
        "metric": ("placements/sec @10K nodes, 100K resident allocs, "
                   "constraints+affinity+spread (BASELINE config 3); "
                   "vs_baseline = geomean placement-throughput ratio "
                   "over ALL FIVE configs (1 = interactive latency via "
                   "the native in-process solver, 2-5 = streamed "
                   "throughput) against the stock-semantics C++ "
                   "engine"),
        "value": primary["ours"]["placements_per_sec"],
        "unit": "placements/sec",
        "vs_baseline": round(geomean, 3) if geomean is not None else None,
    }))


if __name__ == "__main__":
    main()
